#!/usr/bin/env python
"""Chaos smoke: preempt a ``bench.py --smoke`` run mid-iteration and prove the
fault-tolerant runtime end-to-end:

1. launch ``python bench.py --smoke`` with the deterministic failpoint
   ``preempt.iteration:signal:SIGTERM:hit=N`` (core/failpoints.py): the child
   delivers SIGTERM to ITSELF at the end of training iteration N, exactly
   between iterations — no parent-side ready-file polling race, identical
   injection point on every run and every machine;
2. assert the process still exits 0 (the PreemptionGuard converts the signal
   into an end-of-iteration stop + emergency checkpoint; bench's remaining
   pass runs normally and its one-JSON-line stdout contract holds);
3. assert the emergency checkpoint exists — bench smoke sets
   ``checkpoint.every=999999999`` and ``save_last=False``, so the ONLY ``.ckpt``
   on disk is the guard's emergency save;
4. resume from it in a fresh process (failpoint NOT set) and assert exit 0.

Run directly (``python scripts/chaos_smoke.py``) or through the registered
tier-1 test (tests/test_utils/test_chaos_smoke.py). The companion rollback
drill — a chaos DIVERGENCE fault (reward spike) that the health sentinel must
detect and answer by restoring a certified checkpoint — lives in
``scripts/health_smoke.py`` with the same harness shape.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from sheeprl_tpu.core import failpoints  # noqa: E402


def _find_ckpts(root: str) -> list:
    found = []
    for base, _, files in os.walk(root):
        found += [os.path.join(base, f) for f in files if f.endswith(".ckpt")]
    return sorted(found)


def main(workdir: str | None = None, timeout: float = 540.0, preempt_at_iter: int = 2) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_smoke_")
    os.makedirs(workdir, exist_ok=True)
    ready_file = os.path.join(workdir, "guard_ready")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SHEEPRL_PREEMPTION_READY_FILE=ready_file,
        # self-preemption at a deterministic iteration boundary (the old
        # parent-side SIGTERM raced process startup and iteration timing)
        SHEEPRL_TPU_FAILPOINTS=failpoints.spec_entry(
            "preempt.iteration", "signal", "SIGTERM", f"hit={preempt_at_iter}"
        ),
    )
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"), "--smoke"],
        cwd=workdir,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout)
    except BaseException:
        proc.kill()
        raise
    if proc.returncode != 0:
        raise SystemExit(
            f"self-preempted bench run exited rc={proc.returncode} (expected a clean 0); "
            f"stderr tail:\n{err[-2000:]}"
        )
    if not os.path.exists(ready_file):
        raise SystemExit(
            "the preemption guard never armed (ready file missing) — the failpoint "
            "SIGTERM would have killed the process, yet it exited 0: injection did not run"
        )
    # bench's stdout contract: the LAST line is the one JSON result record
    last_line = out.strip().splitlines()[-1] if out.strip() else ""
    json.loads(last_line)

    ckpts = _find_ckpts(os.path.join(workdir, "logs"))
    if not ckpts:
        raise SystemExit("no emergency checkpoint found after the injected preemption")

    resume_env = dict(os.environ, JAX_PLATFORMS="cpu")
    resume_env.pop("SHEEPRL_TPU_FAILPOINTS", None)  # resume runs fault-free
    resume = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "sheeprl.py"),
            # the CLI builds a full config BEFORE merging the sidecar, so the
            # mandatory exp group (and the env/algo identity it implies) must be
            # respecified; everything else is restored from the checkpoint's run
            "exp=ppo",
            "env=dummy",
            "env.capture_video=False",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            f"checkpoint.resume_from={os.path.abspath(ckpts[-1])}",
            "checkpoint.save_last=False",
            "checkpoint.every=999999999",
        ],
        cwd=workdir,
        env=resume_env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if resume.returncode != 0:
        raise SystemExit(
            f"resume from the emergency checkpoint exited rc={resume.returncode}; "
            f"stderr tail:\n{resume.stderr[-2000:]}"
        )
    return {"checkpoint": ckpts[-1], "workdir": workdir}


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="run directory (default: fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=540.0, help="per-phase timeout in seconds")
    parser.add_argument(
        "--preempt-at-iter", type=int, default=2, help="iteration boundary for the injected SIGTERM"
    )
    args = parser.parse_args()
    result = main(args.workdir, args.timeout, args.preempt_at_iter)
    print(f"chaos smoke OK: injected preempt -> clean exit -> resumable checkpoint {result['checkpoint']}")
