#!/usr/bin/env python
"""Chaos smoke: SIGTERM a ``bench.py --smoke`` run mid-iteration and prove the
fault-tolerant runtime end-to-end:

1. launch ``python bench.py --smoke`` with ``SHEEPRL_PREEMPTION_READY_FILE``
   set, and wait for the PreemptionGuard to touch that file (its signal
   handlers are live from that point, so the SIGTERM below lands mid-iteration
   instead of racing interpreter startup);
2. deliver SIGTERM and assert the process still exits 0 (the guard converts the
   signal into an end-of-iteration stop + emergency checkpoint; bench's
   remaining pass runs normally and its one-JSON-line stdout contract holds);
3. assert the emergency checkpoint exists — bench smoke sets
   ``checkpoint.every=999999999`` and ``save_last=False``, so the ONLY ``.ckpt``
   on disk is the guard's emergency save;
4. resume from it in a fresh process and assert exit 0.

Run directly (``python scripts/chaos_smoke.py``) or through the registered
tier-1 test (tests/test_utils/test_chaos_smoke.py). The companion rollback
drill — a chaos DIVERGENCE fault (reward spike) that the health sentinel must
detect and answer by restoring a certified checkpoint — lives in
``scripts/health_smoke.py`` with the same harness shape.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _find_ckpts(root: str) -> list:
    found = []
    for base, _, files in os.walk(root):
        found += [os.path.join(base, f) for f in files if f.endswith(".ckpt")]
    return sorted(found)


def main(workdir: str | None = None, timeout: float = 540.0) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_smoke_")
    os.makedirs(workdir, exist_ok=True)
    ready_file = os.path.join(workdir, "guard_ready")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        SHEEPRL_PREEMPTION_READY_FILE=ready_file,
    )
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"), "--smoke"],
        cwd=workdir,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    deadline = time.time() + timeout
    try:
        while not os.path.exists(ready_file):
            if proc.poll() is not None:
                _, err = proc.communicate()
                raise SystemExit(
                    f"bench exited (rc={proc.returncode}) before the preemption guard "
                    f"armed; stderr tail:\n{err[-2000:]}"
                )
            if time.time() > deadline:
                raise SystemExit("timed out waiting for the preemption guard to arm")
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=max(deadline - time.time(), 1.0))
    except BaseException:
        proc.kill()
        raise
    if proc.returncode != 0:
        raise SystemExit(
            f"SIGTERM'd bench run exited rc={proc.returncode} (expected a clean 0); "
            f"stderr tail:\n{err[-2000:]}"
        )
    # bench's stdout contract: the LAST line is the one JSON result record
    last_line = out.strip().splitlines()[-1] if out.strip() else ""
    json.loads(last_line)

    ckpts = _find_ckpts(os.path.join(workdir, "logs"))
    if not ckpts:
        raise SystemExit("no emergency checkpoint found after SIGTERM")

    resume = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "sheeprl.py"),
            # the CLI builds a full config BEFORE merging the sidecar, so the
            # mandatory exp group (and the env/algo identity it implies) must be
            # respecified; everything else is restored from the checkpoint's run
            "exp=ppo",
            "env=dummy",
            "env.capture_video=False",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            f"checkpoint.resume_from={os.path.abspath(ckpts[-1])}",
            "checkpoint.save_last=False",
            "checkpoint.every=999999999",
        ],
        cwd=workdir,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if resume.returncode != 0:
        raise SystemExit(
            f"resume from the emergency checkpoint exited rc={resume.returncode}; "
            f"stderr tail:\n{resume.stderr[-2000:]}"
        )
    return {"checkpoint": ckpts[-1], "workdir": workdir}


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="run directory (default: fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=540.0, help="per-phase timeout in seconds")
    result = main(parser.parse_args().workdir, parser.parse_args().timeout)
    print(f"chaos smoke OK: SIGTERM -> clean exit -> resumable checkpoint {result['checkpoint']}")
