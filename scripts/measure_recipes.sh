#!/bin/bash
# Wall-clock recipe rows for the README table (run on the real chip, chip idle).
# Dreamer rows use the dummy pixel env at the reference benchmark shapes
# (Atari is an optional dependency, absent here) — same substitution the r4
# measurements made, now with the host-CPU player + amortized param sync.
set -u
cd "$(dirname "$0")/.."
for args in \
  "dreamer_v1 env=dummy env.id=discrete_dummy env.capture_video=False algo.player_sync_every=16" \
  "dreamer_v2 env=dummy env.id=discrete_dummy env.capture_video=False algo.player_sync_every=16" \
  "dreamer_v3 env=dummy env.id=discrete_dummy env.capture_video=False algo.player_sync_every=16" \
  "sac algo.player_sync_every=16" \
  ; do
  echo "=== $args"
  timeout 1800 python benchmarks/benchmark.py $args 2>&1 | tail -1
done
