#!/usr/bin/env python
"""Compiled-program observatory smoke: ledger capture, diff gate, bench sentinel.

Three phases, all in fresh interpreters (the capture path must work from a cold
import, exactly like a real run):

1. **capture** — two tiny fused-PPO iterations on the in-graph CartPole with
   the trace id AND the programs ledger pinned through the env
   (``SHEEPRL_TPU_TRACE`` / ``SHEEPRL_TPU_PROGRAMS``). Every AOT-compiled
   program of the run must land in ``programs.jsonl`` with a non-null
   fingerprint, FLOPs, HBM breakdown and shardings, stamped with the pinned
   trace id — and the fused ``.ingraph_train`` entry point must be among them.
2. **diff** — ``python -m sheeprl_tpu.telemetry.programs diff`` against a
   doctored copy of that ledger (+10% temp-HBM, one resharded input) must exit
   1 and name both regressions; the self-diff must exit 0.
3. **sentinel** — ``python bench.py --check-regressions`` over a synthetic
   4-round ledger must exit 0 clean and 4 after the newest round is doctored
   (SPS halved, p99 quadrupled).

Run directly (``python scripts/obs_smoke.py``) or through the registered
tier-1 test (tests/test_utils/test_obs_smoke.py).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TRACE_ID = "obs-smoke-trace"

_CHILD = r"""
import contextlib, json, os, sys
from sheeprl_tpu.cli import run
from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.telemetry import programs as tel_programs

overrides = json.loads(os.environ["_SHEEPRL_OBS_SMOKE_OVERRIDES"])
with contextlib.redirect_stdout(sys.stderr):
    run(overrides=overrides)

stats = jax_compile.process_stats()
print("OBS_SMOKE " + json.dumps({
    "retraces": stats["retraces"],
    "aot_compiles": stats["aot_compiles"],
    "programs": tel_programs.stats(),
}), flush=True)
"""

# 16 envs x 16 steps = 256 policy steps/iter; 512 total = two fused iterations
_OVERRIDES = [
    "exp=ppo",
    "env=jax_cartpole",
    "env.fused=True",
    "env.num_envs=16",
    "algo.rollout_steps=16",
    "algo.per_rank_batch_size=128",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.run_test=False",
    "algo.total_steps=512",
    "fabric.devices=1",
    "metric.log_level=0",
    "metric.disable_timer=True",
    "checkpoint.every=999999999",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
]


def _child_env(workdir: str, ledger: str) -> dict:
    return dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        SHEEPRL_TPU_COMP_CACHE_DIR=os.path.join(workdir, "xla_cache"),
        SHEEPRL_TPU_TRACE=f"plane=train;capacity=4096;trace_id={_TRACE_ID}",
        SHEEPRL_TPU_PROGRAMS=ledger,
        _SHEEPRL_OBS_SMOKE_OVERRIDES=json.dumps(_OVERRIDES),
    )


def _phase_capture(workdir: str, timeout: float) -> dict:
    ledger = os.path.join(workdir, "programs.jsonl")
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD],
        cwd=workdir,
        env=_child_env(workdir, ledger),
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    tag = "OBS_SMOKE "
    line = next((ln for ln in proc.stdout.splitlines() if ln.startswith(tag)), None)
    if proc.returncode != 0 or line is None:
        raise SystemExit(
            f"capture child failed (rc={proc.returncode});\nstdout tail:\n{proc.stdout[-1000:]}"
            f"\nstderr tail:\n{proc.stderr[-3000:]}"
        )
    stats = json.loads(line[len(tag):])
    if stats["retraces"] != 0:
        raise SystemExit(f"capture: retraces during the fused smoke: {stats['retraces']}")
    if not os.path.isfile(ledger):
        raise SystemExit(f"capture: no programs ledger written at {ledger}")

    with open(ledger) as f:
        rows = [json.loads(ln) for ln in f if ln.strip()]
    if not rows:
        raise SystemExit("capture: programs ledger is empty")
    if len(rows) < stats["aot_compiles"]:
        raise SystemExit(
            f"capture: {stats['aot_compiles']} AOT compiles but only {len(rows)} ledger rows"
        )
    for row in rows:
        for field in ("fingerprint", "flops", "memory", "input_shardings", "output_shardings"):
            if row.get(field) is None:
                raise SystemExit(f"capture: row for {row.get('name')!r} has null {field}")
        if row.get("trace_id") != _TRACE_ID:
            raise SystemExit(
                f"capture: row for {row.get('name')!r} carries trace_id={row.get('trace_id')!r}, "
                f"expected the pinned {_TRACE_ID!r}"
            )
    names = {row["name"] for row in rows}
    if not any(name.endswith(".ingraph_train") for name in names):
        raise SystemExit(f"capture: no fused .ingraph_train program in the ledger: {sorted(names)}")
    return {"rows": len(rows), "programs": sorted(names), "ledger": ledger}


def _doctor_ledger(ledger: str, out_path: str) -> None:
    with open(ledger) as f:
        rows = [json.loads(ln) for ln in f if ln.strip()]
    for row in rows:
        mem = row.get("memory") or {}
        if "temp_bytes" in mem:
            delta = mem["temp_bytes"] * 0.10 or 4096.0
            mem["temp_bytes"] += delta
            mem["peak_bytes"] = mem.get("peak_bytes", 0.0) + delta
        if row.get("input_shardings"):
            row["input_shardings"] = ["NamedSharding(resharded)"] + row["input_shardings"][1:]
    with open(out_path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


def _run_cli(args: list, timeout: float) -> subprocess.CompletedProcess:
    env = dict(os.environ, PYTHONPATH=REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""))
    return subprocess.run(
        [sys.executable] + args,
        cwd=REPO_ROOT,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def _phase_diff(ledger: str, workdir: str, timeout: float) -> dict:
    doctored = os.path.join(workdir, "programs_regressed.jsonl")
    _doctor_ledger(ledger, doctored)

    bad = _run_cli(
        ["-m", "sheeprl_tpu.telemetry.programs", "diff", ledger, doctored, "--json"], timeout
    )
    if bad.returncode != 1:
        raise SystemExit(
            f"diff: doctored ledger must exit 1, got rc={bad.returncode}\n{bad.stdout}\n{bad.stderr[-1000:]}"
        )
    report = json.loads(bad.stdout)
    if not any(d["field"] == "temp_bytes" and d["regression"] for d in report["memory_deltas"]):
        raise SystemExit(f"diff: seeded +10% temp-HBM not flagged: {report['memory_deltas']}")
    if not any(c["io"] == "input_shardings" for c in report["sharding_changes"]):
        raise SystemExit(f"diff: seeded resharding not flagged: {report['sharding_changes']}")

    clean = _run_cli(["-m", "sheeprl_tpu.telemetry.programs", "diff", ledger, ledger], timeout)
    if clean.returncode != 0:
        raise SystemExit(f"diff: self-diff must exit 0, got rc={clean.returncode}\n{clean.stdout}")
    return {"regressions_flagged": len(report["regressions"])}


def _phase_sentinel(workdir: str, timeout: float) -> dict:
    bench_py = os.path.join(REPO_ROOT, "bench.py")
    base = {
        "status": "ok",
        "env_steps_per_sec": 1000.0,
        "infer_p99_ms": 10.0,
        "device_hbm_peak_bytes": 1.0e9,
    }
    ledger = os.path.join(workdir, "bench_ledger.jsonl")
    with open(ledger, "w") as f:
        for i in range(4):
            f.write(json.dumps(dict(base, run_id=f"r{i}")) + "\n")
    clean = _run_cli([bench_py, "--check-regressions", "--ledger", ledger], timeout)
    if clean.returncode != 0:
        raise SystemExit(
            f"sentinel: clean ledger must exit 0, got rc={clean.returncode}\n{clean.stdout}\n{clean.stderr[-500:]}"
        )
    with open(ledger, "a") as f:
        f.write(
            json.dumps(dict(base, run_id="bad", env_steps_per_sec=500.0, infer_p99_ms=40.0)) + "\n"
        )
    bad = _run_cli([bench_py, "--check-regressions", "--ledger", ledger], timeout)
    if bad.returncode != 4:
        raise SystemExit(
            f"sentinel: doctored ledger must exit 4, got rc={bad.returncode}\n{bad.stdout}\n{bad.stderr[-500:]}"
        )
    report = json.loads(bad.stdout.splitlines()[-1])
    for key in ("env_steps_per_sec", "infer_p99_ms"):
        if key not in report["regressions"]:
            raise SystemExit(f"sentinel: {key} breach not reported: {report['regressions']}")
    return {"clean_rc": clean.returncode, "doctored_rc": bad.returncode}


def main(workdir: str | None = None, timeout: float = 480.0) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="obs_smoke_")
    os.makedirs(workdir, exist_ok=True)
    results = {"capture": _phase_capture(workdir, timeout)}
    results["diff"] = _phase_diff(results["capture"]["ledger"], workdir, timeout)
    results["sentinel"] = _phase_sentinel(workdir, timeout)
    print(f"obs smoke OK: {json.dumps(results)}")
    return results


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="scratch dir (default: a fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=480.0, help="per-phase timeout in seconds")
    cli = parser.parse_args()
    main(cli.workdir, cli.timeout)
