#!/usr/bin/env python
"""Transport smoke: two-process epoch-fenced chunk-transport drill.

Proves the host control plane (sheeprl_tpu/parallel/control.py) delivers a
chunk stream with **zero lost and zero duplicated chunks** while the failpoint
registry (core/failpoints.py) injects every transport fault class:

1. a parent process runs a :class:`KVServer` (the drill's stand-in for the jax
   coordinator KV store) and spawns a **consumer** and a **player** child that
   talk through :class:`SocketKV` — both children are jax-free;
2. the phase-1 player sends chunks with ``control.chunk_send:drop:every=3``
   (silently lost writes → ack-poll timeout → resend) and is then KILLED by a
   ``transport.player_crash:kill`` failpoint mid-stream — a preemption with no
   cleanup;
3. the parent restarts the player. The new incarnation bumps the fenced
   session epoch, reads the durable reader cursor, and resumes at exactly
   ``cursor + 1``. Its sends run under ``control.chunk_send:corrupt`` (torn
   payloads → CRC nack → resend) while the consumer delays its acks with a
   ``control.kv_set:sleep`` failpoint;
4. after the epoch bump, the parent forges a **zombie write** — a
   well-formed, CRC-valid chunk stamped with the dead incarnation's epoch —
   onto the next sequence number. The consumer must reject it against the
   authoritative epoch key (``Resilience/stale_epoch_rejects >= 1``) and nack
   ``stale``; the live writer must shrug off the foreign stale and resend;
5. audit: the consumer's per-chunk CRCs equal the expected stream exactly
   (order, count, content), the cursor ends at the last seq, the restarted
   player resumed at the right offset in epoch 2 with at least one resend,
   and the player's heartbeats made it visible to ``peer_liveness``.

Run directly (``python scripts/transport_smoke.py``) or through the registered
tier-1 test (tests/test_utils/test_transport_smoke.py). ``bench.py --target
transport`` reuses the same KVServer/SocketKV pair for latency numbers.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import subprocess
import sys
import time
import zlib

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from sheeprl_tpu.core import failpoints  # noqa: E402
from sheeprl_tpu.parallel.control import ControlPlane, SocketKV  # noqa: E402

CHANNEL = "roll"
SCOPE = "transport_smoke"
ROLE = "player"
ZOMBIE_PAYLOAD = b"ZOMBIE-PAYLOAD-FROM-A-DEAD-INCARNATION"


def _chunk_data(seq: int) -> bytes:
    """Deterministic per-seq payload, big enough that corruption lands in the
    b64 body and distinctive enough that a swap/dup is unmistakable."""
    return (f"chunk-{seq:04d}|".encode() * 8) + bytes([seq % 256]) * 64


def _expected_crcs(total: int) -> list:
    return [zlib.crc32(_chunk_data(i)) & 0xFFFFFFFF for i in range(total)]


# --------------------------------------------------------------------------- children
def run_player(addr: str, total: int, resume: bool, hold_s: float) -> None:
    from sheeprl_tpu.core import failpoints

    kv = SocketKV(addr)
    plane = ControlPlane(kv, rank=0, world=2, scope=SCOPE, timeout_ms=30_000)
    epoch = plane.begin_session(ROLE)
    start = plane.chunk_cursor(CHANNEL) + 1 if resume else 0
    if hold_s > 0:
        # leave the parent a window to forge the zombie write AFTER our epoch
        # bump but BEFORE our first envelope — the hardest fencing case
        time.sleep(hold_s)
    for seq in range(start, total):
        plane.send_chunk(CHANNEL, seq, _chunk_data(seq), timeout_ms=30_000)
        plane.heartbeat({"seq": seq})
        # phase 1 dies here mid-stream via transport.player_crash:kill:...
        failpoints.failpoint("transport.player_crash", seq=seq)
    print(json.dumps({"role": "player", "epoch": epoch, "start": start, "counters": plane.counters}))


def run_consumer(addr: str, total: int) -> None:
    kv = SocketKV(addr)
    plane = ControlPlane(kv, rank=1, world=2, scope=SCOPE, timeout_ms=30_000)
    plane.adopt_epoch(ROLE)
    crcs = []
    for seq in range(total):
        data = plane.recv_chunk(CHANNEL, seq, timeout_ms=120_000)
        crcs.append(zlib.crc32(data) & 0xFFFFFFFF)
    liveness = plane.peer_liveness(max_age_s=60.0)
    print(
        json.dumps(
            {
                "role": "consumer",
                "crcs": crcs,
                "cursor": plane.chunk_cursor(CHANNEL),
                "counters": plane.counters,
                "player_alive": liveness.get(0, {}).get("alive"),
            }
        )
    )


# --------------------------------------------------------------------------- parent
def _spawn(args: list, failpoints_spec: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.pop("SHEEPRL_TPU_FAILPOINTS", None)
    if failpoints_spec:
        env["SHEEPRL_TPU_FAILPOINTS"] = failpoints_spec
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)] + args,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


def _result(proc: subprocess.Popen, label: str, timeout: float) -> dict:
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate()
        raise SystemExit(f"{label} hung; stdout:\n{out[-2000:]}\nstderr:\n{err[-2000:]}")
    if proc.returncode != 0:
        raise SystemExit(f"{label} exited rc={proc.returncode}; stderr tail:\n{err[-2000:]}")
    last = out.strip().splitlines()[-1] if out.strip() else ""
    try:
        return json.loads(last)
    except ValueError:
        raise SystemExit(f"{label} printed no JSON result; stdout tail:\n{out[-2000:]}")


def _poll(pred, timeout_s: float, what: str):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        got = pred()
        if got is not None:
            return got
        time.sleep(0.01)
    raise SystemExit(f"timed out after {timeout_s}s waiting for {what}")


def main(total: int = 12, crash_after: int = 4, timeout: float = 300.0) -> dict:
    from sheeprl_tpu.parallel.control import KVServer

    if not 0 < crash_after < total:
        raise SystemExit(f"crash_after must be in (0, {total}), got {crash_after}")
    server = KVServer()
    server.start()
    kv = SocketKV(server.address)
    plane = ControlPlane(kv, rank=99, world=2, scope=SCOPE)  # parent's key helper only
    started = time.monotonic()
    try:
        consumer = _spawn(
            ["--role", "consumer", "--addr", server.address, "--total", str(total)],
            # delayed acks: the writer's ack-poll must tolerate a slow reader
            failpoints.spec_entry("control.kv_set", "sleep", "0.05", "every=5"),
        )

        # phase 1: drops + a mid-stream kill after `crash_after` sent chunks
        player1 = _spawn(
            ["--role", "player", "--addr", server.address, "--total", str(total)],
            ",".join(
                [
                    failpoints.spec_entry("control.chunk_send", "drop", trigger="every=3"),
                    failpoints.spec_entry(
                        "transport.player_crash", "kill", "9", f"hit={crash_after}"
                    ),
                ]
            ),
        )
        p1_out, p1_err = player1.communicate(timeout=timeout)
        if player1.returncode != 9:
            raise SystemExit(
                f"phase-1 player should die by its kill failpoint (rc 9), got rc="
                f"{player1.returncode}; stderr tail:\n{p1_err[-2000:]}\nstdout:\n{p1_out[-500:]}"
            )
        cursor = _poll(
            lambda: (lambda c: c if c >= crash_after - 1 else None)(plane.chunk_cursor(CHANNEL)),
            30.0,
            f"reader cursor to reach {crash_after - 1} after the player crash",
        )

        # phase 2: restart; new epoch, resume at cursor+1, torn payloads
        player2 = _spawn(
            [
                "--role", "player", "--addr", server.address, "--total", str(total),
                "--resume", "--hold-s", "1.2",
            ],
            "control.chunk_send:corrupt:2:every=4",
        )
        # zombie forge: wait for the successor's epoch bump, then write a
        # CRC-valid envelope stamped with the DEAD epoch onto the next seq
        epoch2 = _poll(
            lambda: (lambda e: e if e is not None and int(e) >= 2 else None)(
                kv.try_get(plane._epoch_key(ROLE), timeout_ms=50)
            ),
            30.0,
            "the restarted player to bump the session epoch",
        )
        forged_seq = cursor + 1
        data_key, _ = plane._chunk_keys(CHANNEL, forged_seq)
        forged = (
            f"1:{forged_seq}:{zlib.crc32(ZOMBIE_PAYLOAD) & 0xFFFFFFFF}:"
            + base64.b64encode(ZOMBIE_PAYLOAD).decode()
        )
        kv.set(data_key, forged)

        p2 = _result(player2, "phase-2 player", timeout)
        cons = _result(consumer, "consumer", timeout)
    finally:
        server.stop()

    # ---- audit ---------------------------------------------------------------
    expected = _expected_crcs(total)
    if cons["crcs"] != expected:
        raise SystemExit(
            f"chunk stream damaged: expected {total} chunks with CRCs {expected}, "
            f"got {cons['crcs']} (zombie CRC is {zlib.crc32(ZOMBIE_PAYLOAD) & 0xFFFFFFFF})"
        )
    if cons["cursor"] != total - 1:
        raise SystemExit(f"reader cursor ended at {cons['cursor']}, want {total - 1}")
    stale_rejects = cons["counters"]["Resilience/stale_epoch_rejects"]
    if stale_rejects < 1:
        raise SystemExit("the forged zombie write was never rejected (stale_epoch_rejects=0)")
    if p2["epoch"] != int(epoch2) or p2["start"] != crash_after:
        raise SystemExit(
            f"restart did not resume correctly: epoch={p2['epoch']} (want {epoch2}), "
            f"start={p2['start']} (want {crash_after})"
        )
    if p2["counters"]["Resilience/chunk_resends"] < 1:
        raise SystemExit("torn payloads never forced a resend — the corrupt failpoint did not bite")
    if p2["counters"]["Resilience/heartbeats_sent"] < 1 or cons.get("player_alive") is not True:
        raise SystemExit(f"player heartbeats not visible to peer_liveness: {cons.get('player_alive')}")

    return {
        "total_chunks": total,
        "crash_after": crash_after,
        "resumed_at": p2["start"],
        "epochs": [1, p2["epoch"]],
        "stale_epoch_rejects": stale_rejects,
        "writer_resends": p2["counters"]["Resilience/chunk_resends"],
        "consumer_counters": cons["counters"],
        "wall_s": round(time.monotonic() - started, 2),
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--role", choices=["parent", "player", "consumer"], default="parent")
    parser.add_argument("--addr", default=None, help="KV server address (child roles)")
    parser.add_argument("--total", type=int, default=12, help="chunks in the stream")
    parser.add_argument("--crash-after", type=int, default=4, help="sent chunks before the injected kill")
    parser.add_argument("--resume", action="store_true", help="player: resume from the durable cursor")
    parser.add_argument("--hold-s", type=float, default=0.0, help="player: pause between epoch bump and first send")
    parser.add_argument("--timeout", type=float, default=300.0, help="parent: per-child budget in seconds")
    cli = parser.parse_args()
    if cli.role == "player":
        run_player(cli.addr, cli.total, cli.resume, cli.hold_s)
    elif cli.role == "consumer":
        run_consumer(cli.addr, cli.total)
    else:
        result = main(cli.total, cli.crash_after, cli.timeout)
        print(
            "transport smoke OK: "
            f"{result['total_chunks']} chunks across a mid-stream kill/restart "
            f"(resumed at #{result['resumed_at']}, epochs {result['epochs']}), "
            f"{result['stale_epoch_rejects']} zombie write(s) fenced, "
            f"{result['writer_resends']} resend(s) under drops/torn payloads "
            f"({result['wall_s']}s)"
        )
