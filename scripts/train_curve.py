"""Run a pixel-target learning demonstration and emit a reward-vs-step curve.

Trains via the real CLI, scrapes the per-episode reward lines the training
loops print (``Rank-0: policy_step=N, reward_env_i=R``), and writes
``benchmarks/results/<name>_curve.csv`` (+ a PNG with a running mean). A
timeout still yields a partial curve from whatever output was captured.

Usage: python scripts/train_curve.py <name> <timeout_s> <override> [...]
e.g.:  python scripts/train_curve.py dreamer_v1_pixel_target 5400 exp=dreamer_v1_pixel_target
"""

from __future__ import annotations

import os
import re
import subprocess
import sys

_LINE = re.compile(
    r"policy_step=(\d+), reward_env_\d+="
    r"([-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|\.\d+(?:[eE][-+]?\d+)?|nan|inf))"
)


def parse_curve(text: str):
    return [(int(m.group(1)), float(m.group(2))) for m in _LINE.finditer(text)]


def write_outputs(name: str, points, out_dir: str) -> None:
    os.makedirs(out_dir, exist_ok=True)
    csv_path = os.path.join(out_dir, f"{name}_curve.csv")
    with open(csv_path, "w") as f:
        for step, rew in points:
            f.write(f"{step},{rew}\n")
    print(f"wrote {csv_path} ({len(points)} episodes)")
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        import numpy as np

        steps = np.array([p[0] for p in points])
        rews = np.array([p[1] for p in points])
        k = max(1, len(rews) // 50)
        running = np.convolve(rews, np.ones(k) / k, mode="valid")
        fig, ax = plt.subplots(figsize=(7, 4))
        ax.plot(steps, rews, ".", ms=2, alpha=0.25, label="episode reward")
        ax.plot(steps[k - 1 :], running, lw=2, label=f"running mean (k={k})")
        ax.set_xlabel("policy step")
        ax.set_ylabel("episode reward")
        ax.set_title(name)
        ax.legend()
        fig.tight_layout()
        fig.savefig(os.path.join(out_dir, f"{name}_curve.png"), dpi=120)
        print(f"wrote {name}_curve.png")
    except Exception as e:  # PNG is best-effort; the CSV is the artifact
        print(f"PNG skipped: {type(e).__name__}: {e}")


def main() -> None:
    name, timeout_s, overrides = sys.argv[1], int(sys.argv[2]), sys.argv[3:]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, os.path.join(repo, "sheeprl.py")] + overrides + ["metric.log_level=1"]
    try:
        proc = subprocess.run(cmd, cwd=repo, capture_output=True, text=True, timeout=timeout_s)
        out = (proc.stdout or "") + (proc.stderr or "")
        status = proc.returncode
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        out += (e.stderr or b"").decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        status = "timeout"
    points = parse_curve(out)
    write_outputs(name, points, os.path.join(repo, "benchmarks", "results"))
    print(f"run status: {status}, episodes: {len(points)}")
    if status not in (0, "timeout"):
        # always surface the failure, even with a partial curve in hand
        tail = "\n".join(l for l in out.splitlines() if "cpu_aot_loader" not in l)
        print(f"--- run tail ---\n{tail[-4000:]}", file=sys.stderr)
    if not points:
        sys.exit(1)


if __name__ == "__main__":
    main()
