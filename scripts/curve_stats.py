"""Summarize a reward curve: peak, late-window stability, verdict-style stats.

Reads either a ``<name>_curve.csv`` (step,reward rows) or a raw training log
(scraped with train_curve's regex), writes the CSV/PNG via train_curve's
helpers when given a log, and prints a stability summary:

- running-mean peak (window k = n/50, the PNG's smoothing),
- final-20%-window mean and its ratio to the peak,
- episode count and step span.

Usage:
  python scripts/curve_stats.py benchmarks/results/dv3_dmc_walker_walk_curve.csv
  python scripts/curve_stats.py /tmp/walker_r5.log --emit dv3_dmc_walker_walk
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from train_curve import parse_curve, write_outputs  # noqa: E402


def load_points(path: str):
    if path.endswith(".csv"):
        pts = []
        with open(path) as f:
            for line in f:
                step, rew = line.strip().split(",")
                pts.append((int(step), float(rew)))
        return pts
    with open(path) as f:
        return parse_curve(f.read())


def stats(points) -> dict:
    steps = np.array([p[0] for p in points], dtype=np.int64)
    rews = np.array([p[1] for p in points], dtype=np.float64)
    k = max(1, len(rews) // 50)
    running = np.convolve(rews, np.ones(k) / k, mode="valid")
    peak = float(running.max())
    peak_step = int(steps[k - 1 :][int(running.argmax())])
    cutoff = steps[0] + (steps[-1] - steps[0]) * 0.8
    late = rews[steps >= cutoff]
    late_mean = float(late.mean()) if late.size else float("nan")
    return {
        "episodes": len(points),
        "first_step": int(steps[0]),
        "last_step": int(steps[-1]),
        "running_peak": round(peak, 2),
        "peak_step": peak_step,
        "late20_mean": round(late_mean, 2),
        "late20_episodes": int(late.size),
        "late20_over_peak": round(late_mean / peak, 3) if peak else None,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", help="curve CSV or raw training log")
    ap.add_argument("--emit", default=None, help="also write <name>_curve.{csv,png} from a log")
    args = ap.parse_args()
    points = load_points(args.path)
    if not points:
        print("no reward points found", file=sys.stderr)
        sys.exit(1)
    if args.emit:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        write_outputs(args.emit, points, os.path.join(repo, "benchmarks", "results"))
    for k, v in stats(points).items():
        print(f"{k}: {v}")


if __name__ == "__main__":
    main()
