"""Sweep DV3 train-step configs on the real chip and print a table.

Usage: python scripts/mfu_sweep.py [name ...]
Each named config reruns bench.bench_dv3 with different batch/unroll/precision.
"""

from __future__ import annotations

import contextlib
import json
import sys

sys.path.insert(0, ".")
from bench import bench_dv3  # noqa: E402

CONFIGS = {
    "b16": dict(batch=16),
    "b64": dict(batch=64),
    "b64_du4": dict(batch=64, extra_overrides=["algo.world_model.dynamic_scan_unroll=4"]),
    "b64_du8": dict(batch=64, extra_overrides=["algo.world_model.dynamic_scan_unroll=8"]),
    "b64_iu5": dict(batch=64, extra_overrides=["algo.imagination_scan_unroll=5"]),
    "b64_du8_iu5": dict(
        batch=64,
        extra_overrides=[
            "algo.world_model.dynamic_scan_unroll=8",
            "algo.imagination_scan_unroll=5",
        ],
    ),
    "b64_bf16true": dict(batch=64, extra_overrides=["fabric.precision=bf16-true"]),
    "b64_du8_iu5_bf16true": dict(
        batch=64,
        extra_overrides=[
            "algo.world_model.dynamic_scan_unroll=8",
            "algo.imagination_scan_unroll=5",
            "fabric.precision=bf16-true",
        ],
    ),
    "b128": dict(batch=128),
    "b128_du8_iu5": dict(
        batch=128,
        extra_overrides=[
            "algo.world_model.dynamic_scan_unroll=8",
            "algo.imagination_scan_unroll=5",
        ],
    ),
    "b128_iu5": dict(batch=128, extra_overrides=["algo.imagination_scan_unroll=5"]),
    "b128_iu15": dict(batch=128, extra_overrides=["algo.imagination_scan_unroll=15"]),
    "b128_du4_iu5": dict(
        batch=128,
        extra_overrides=[
            "algo.world_model.dynamic_scan_unroll=4",
            "algo.imagination_scan_unroll=5",
        ],
    ),
    "b192_du4_iu5": dict(
        batch=192,
        extra_overrides=[
            "algo.world_model.dynamic_scan_unroll=4",
            "algo.imagination_scan_unroll=5",
        ],
    ),
    "b256_du4_iu5": dict(
        batch=256,
        extra_overrides=[
            "algo.world_model.dynamic_scan_unroll=4",
            "algo.imagination_scan_unroll=5",
        ],
    ),
    "b128_iu15": dict(batch=128, extra_overrides=["algo.imagination_scan_unroll=15"]),
}

if __name__ == "__main__":
    names = sys.argv[1:] or list(CONFIGS)
    rows = []
    for name in names:
        kw = CONFIGS[name]
        with contextlib.redirect_stdout(sys.stderr):
            try:
                r = bench_dv3(iters=20, **kw)
            except Exception as e:
                r = {"error": f"{type(e).__name__}: {e}"}
        r["config"] = name
        rows.append(r)
        print(json.dumps(r), flush=True)
    print("\n== summary ==", file=sys.stderr)
    for r in rows:
        print(
            f"{r['config']:>22}: mfu={r.get('dv3_mfu')} gsps={r.get('dv3_gsteps_per_sec')} "
            f"fps={r.get('dv3_frames_per_sec')} tflops={r.get('dv3_step_tflops')} err={r.get('error')}",
            file=sys.stderr,
        )
