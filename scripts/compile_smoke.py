#!/usr/bin/env python
"""Compile smoke: prove the persistent compilation cache end-to-end on CPU.

Runs the same tiny PPO workload twice in fresh interpreters against ONE
temporary on-disk compilation cache:

1. the COLD child starts with an empty cache directory, so every jitted hot
   path (packed act, fused train step, GAE, metric drain) is compiled by XLA
   and written to the cache;
2. the WARM child replays those executables from disk — it must record
   strictly fewer cache misses than the cold child and at least one cache hit,
   or the cache wiring (``sheeprl_tpu/__init__.py`` + ``configs/compile/``) is
   broken.

Each child also reports the retrace-guard totals, so the smoke doubles as an
assertion that two identical runs see identical abstract signatures (zero
steady-state retraces).

Run directly (``python scripts/compile_smoke.py``) or through the registered
tier-1 test (tests/test_utils/test_compile_smoke.py).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import contextlib, json, os, sys
from sheeprl_tpu.cli import run
from sheeprl_tpu.core import compile as jax_compile

overrides = json.loads(os.environ["_SHEEPRL_COMPILE_SMOKE_OVERRIDES"])
with contextlib.redirect_stdout(sys.stderr):
    run(overrides=overrides)
stats = jax_compile.process_stats()
print("COMPILE_SMOKE " + json.dumps({
    "cache_hits": stats["cache_hits"],
    "cache_misses": stats["cache_misses"],
    "retraces": stats["retraces"],
    "traces": stats["traces"],
    "aot_compiles": stats["aot_compiles"],
}), flush=True)
"""

OVERRIDES = [
    "exp=ppo",
    "algo.total_steps=64",
    "algo.rollout_steps=16",
    "algo.per_rank_batch_size=8",
    "algo.update_epochs=1",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.run_test=False",
    "metric.log_level=0",
    "metric.disable_timer=True",
    "checkpoint.every=999999999",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
    "fabric.devices=1",
]


def _run_child(env: dict, workdir: str, timeout: float) -> dict:
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD],
        cwd=workdir,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    line = next((ln for ln in proc.stdout.splitlines() if ln.startswith("COMPILE_SMOKE ")), None)
    if proc.returncode != 0 or line is None:
        raise SystemExit(
            f"child run failed (rc={proc.returncode});\nstdout tail:\n{proc.stdout[-1000:]}"
            f"\nstderr tail:\n{proc.stderr[-3000:]}"
        )
    return json.loads(line[len("COMPILE_SMOKE "):])


def main(workdir: str | None = None, timeout: float = 480.0) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="compile_smoke_")
    os.makedirs(workdir, exist_ok=True)
    cache_dir = os.path.join(workdir, "xla_cache")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        SHEEPRL_TPU_COMP_CACHE_DIR=cache_dir,
        # the smoke's kernels are tiny and compile in milliseconds: cache them
        # all, or the warm pass would legitimately miss everything
        SHEEPRL_TPU_COMP_CACHE_MIN_SECS="0",
        _SHEEPRL_COMPILE_SMOKE_OVERRIDES=json.dumps(OVERRIDES),
    )
    cold = _run_child(env, workdir, timeout)
    if not os.listdir(cache_dir):
        raise SystemExit(f"cold run left the persistent cache at {cache_dir} empty")
    warm = _run_child(env, workdir, timeout)

    if warm["cache_misses"] >= cold["cache_misses"]:
        raise SystemExit(
            f"warm run recompiled as much as the cold one: cold misses="
            f"{cold['cache_misses']}, warm misses={warm['cache_misses']}"
        )
    if warm["cache_hits"] <= 0:
        raise SystemExit("warm run served zero executables from the persistent cache")
    if warm["retraces"] != 0 or cold["retraces"] != 0:
        raise SystemExit(f"retraces during the smoke: cold={cold['retraces']}, warm={warm['retraces']}")

    result = {"cold": cold, "warm": warm, "cache_dir": cache_dir}
    print(f"compile smoke OK: {json.dumps(result)}")
    return result


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="scratch dir (default: a fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=480.0, help="per-child timeout in seconds")
    cli = parser.parse_args()
    main(cli.workdir, cli.timeout)
