#!/usr/bin/env python
"""In-graph backend smoke: one short PPO run with ``env.backend=ingraph``.

A fresh interpreter trains PPO on the in-graph CartPole for two iterations
(warmup + steady state) and must finish with ZERO retraces — the fused
``lax.scan`` collector, the train step, and the AOT warmup all agree on their
abstract signatures, or the backend wiring (envs/ingraph/ + data/factory.py +
the algo loops) has drifted. The child then drives the debug ``venv.step``
path with a random policy and reports the finished-episode returns, which must
be finite and non-empty — the cheap end-to-end "the env actually plays
episodes" signal.

Run directly (``python scripts/ingraph_smoke.py``) or through the registered
tier-1 test (tests/test_utils/test_ingraph_smoke.py).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import subprocess
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import contextlib, json, os, sys
import numpy as np
from sheeprl_tpu.cli import run
from sheeprl_tpu.core import compile as jax_compile

overrides = json.loads(os.environ["_SHEEPRL_INGRAPH_SMOKE_OVERRIDES"])
with contextlib.redirect_stdout(sys.stderr):
    run(overrides=overrides)
stats = jax_compile.process_stats()

# the mesh-training chaos seams must have been exercised: the per-shard
# rollout handoff put and the microbatched grad-sync dispatch both carry
# armed `fire` failpoints (SHEEPRL_TPU_FAILPOINTS, set by the parent)
from sheeprl_tpu.core import failpoints

fp_fires = {name: c["fires"] for name, c in failpoints.counts().items()}

# random-policy drive through the debug step path: episodes must finish with
# finite returns (auto-reset keeps every env alive the whole time)
from sheeprl_tpu.config import load_config
from sheeprl_tpu.envs import ingraph as ig

with contextlib.redirect_stdout(sys.stderr):
    cfg = load_config(overrides=overrides)
    venv = ig.make_vector_env(cfg, 8, 123)
    venv.reset(seed=123)
    rng = np.random.default_rng(0)
    returns = []
    for _ in range(64):
        _obs, _rew, term, trunc, info = venv.step(rng.integers(0, 2, size=(8,)))
        done = np.logical_or(term, trunc)
        returns.extend(float(r) for r in info["episode_returns"][done])

print("INGRAPH_SMOKE " + json.dumps({
    "retraces": stats["retraces"],
    "traces": stats["traces"],
    "aot_compiles": stats["aot_compiles"],
    "n_episodes": len(returns),
    "mean_return": (sum(returns) / len(returns)) if returns else None,
    "failpoint_fires": fp_fires,
}), flush=True)
"""

OVERRIDES = [
    "exp=ppo",
    "env=jax_cartpole",
    "env.num_envs=16",
    "algo.total_steps=512",  # 2 iterations: warmup + one steady-state (retrace check)
    "algo.rollout_steps=16",
    "algo.per_rank_batch_size=128",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.run_test=False",
    "algo.grad_microbatches=2",  # the accumulation scan must hold on the fused path too
    "metric.log_level=0",
    "metric.disable_timer=True",
    "checkpoint.every=999999999",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
    "fabric.devices=1",
]


def main(workdir: str | None = None, timeout: float = 480.0) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="ingraph_smoke_")
    os.makedirs(workdir, exist_ok=True)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        SHEEPRL_TPU_COMP_CACHE_DIR=os.path.join(workdir, "xla_cache"),
        _SHEEPRL_INGRAPH_SMOKE_OVERRIDES=json.dumps(OVERRIDES),
        # arm the grad-sync chaos seam in benign `fire` mode: the fused run must
        # actually pass through the microbatched update dispatch every iteration
        # (the handoff seam has no site here — fused data never leaves the
        # device; the decoupled FSDP tests drill handoff.shard_put instead)
        SHEEPRL_TPU_FAILPOINTS="train.grad_sync:fire",
    )
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD],
        cwd=workdir,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    line = next((ln for ln in proc.stdout.splitlines() if ln.startswith("INGRAPH_SMOKE ")), None)
    if proc.returncode != 0 or line is None:
        raise SystemExit(
            f"child run failed (rc={proc.returncode});\nstdout tail:\n{proc.stdout[-1000:]}"
            f"\nstderr tail:\n{proc.stderr[-3000:]}"
        )
    stats = json.loads(line[len("INGRAPH_SMOKE "):])

    if stats["retraces"] != 0:
        raise SystemExit(f"retraces during the ingraph smoke: {stats['retraces']}")
    if stats["n_episodes"] <= 0:
        raise SystemExit("no episode finished in 64 random-policy steps x 8 envs")
    if stats["mean_return"] is None or not math.isfinite(stats["mean_return"]):
        raise SystemExit(f"non-finite mean episode return: {stats['mean_return']}")
    fires = stats.get("failpoint_fires") or {}
    if int(fires.get("train.grad_sync", 0)) < 1:
        raise SystemExit(
            "failpoint 'train.grad_sync' never fired during the smoke — the run did "
            f"not pass through the grad-sync dispatch seam (fires: {json.dumps(fires)})"
        )

    print(f"ingraph smoke OK: {json.dumps(stats)}")
    return stats


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="scratch dir (default: a fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=480.0, help="child timeout in seconds")
    cli = parser.parse_args()
    main(cli.workdir, cli.timeout)
