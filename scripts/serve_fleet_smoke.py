#!/usr/bin/env python
"""Fleet smoke: chaos drill for the replica-fleet serving plane.

1. synthesize a tiny certified checkpoint (serve_smoke's fixture), then launch
   the fleet supervisor (``python -m sheeprl_tpu.serve.fleet``) with 3 serve
   replicas behind the failover router. The SUPERVISOR carries a one-shot
   ``fleet.deploy:raise`` failpoint so the first rolling deploy below
   deterministically fails its canary and must roll back fleet-wide;
2. drive sustained mixed-priority closed-loop load (priority-0 best-effort +
   priority-1 clients) through the router;
3. priority proof: with the background clients quiesced, pipeline a burst of
   priority-0 requests plus a handful of priority-1 through the router against
   tiny replica queues (depth 8, ``shed_oldest``). The p1 population is kept
   strictly below one queue's depth, so a p1 shed is IMPOSSIBLE if the policy
   is right: every shed id must be p0-tagged and every shed response must
   carry the ``retry_after_ms`` hint;
4. SIGKILL one replica mid-load: the router fails the in-flight relays over to
   the survivors (zero client-visible errors), the supervisor classifies the
   exit and respawns the slot under a NEW fenced epoch
   (``Fleet/replica_restarts >= 1``, epoch bumped in the membership file);
5. rolling certified deploy under load: certify a step-200 generation; the
   injected canary failure must roll the fleet back
   (``Fleet/deploy_rollbacks >= 1``) before the retry lands it
   (``Fleet/deploys >= 1``, every member re-stamped with the new artifact);
6. forged zombie write: append a duplicate member for slot 0 with epoch 0
   pointing at a trap listener directly into the membership file. The router
   must fence it (``Fleet/fenced_writes >= 1``) and the trap must see ZERO
   connections — a stale epoch never answers anything;
7. SIGTERM the supervisor with clients still in flight: router drains
   (rejected/draining is still a response), every replica drains to rc 0, the
   fleet stats file reports a clean fleet-wide drain, and the router counters
   satisfy ``requests_total == ok + shed + rejected + deadline_missed +
   errors`` at shutdown — every request that ever reached the fleet got
   exactly one answer.

Run directly (``python scripts/serve_fleet_smoke.py``) or through the
registered slow-marked test (tests/test_utils/test_serve_fleet_smoke.py;
the tier-1 `-m fleet` tests cover the same contracts against stub replicas).
``bench.py --target serve_fleet`` reuses :func:`launch_fleet` for its
SLO-gated kill+deploy QPS sweep.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from sheeprl_tpu.core import failpoints  # noqa: E402


def _load_serve_smoke():
    spec = importlib.util.spec_from_file_location(
        "serve_smoke", os.path.join(REPO_ROOT, "scripts", "serve_smoke.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


serve_smoke = _load_serve_smoke()

# Per-replica serve knobs: queues small enough that the priority burst below
# overflows them deterministically, deadlines long enough that nothing times
# out on a slow CPU box.
REPLICA_OVERRIDES = [
    "serve.batch.max_size=4",
    "serve.batch.max_wait_ms=4.0",
    "serve.queue.max_depth=8",
    "serve.queue.admission=shed_oldest",
    "serve.queue.deadline_ms=30000",
]

FLEET_OVERRIDES = [
    "fleet.replicas=3",
    "fleet.heartbeat_s=0.2",
    "fleet.restart_backoff_s=0.2",
    "fleet.restart_backoff_max_s=0.5",
    "fleet.deploy_poll_s=0.25",
    "fleet.deploy_retry_s=0.5",
    "fleet.drain_timeout_s=90",
    "router.membership_poll_s=0.05",
]


# --------------------------------------------------------------------------- fleet
def launch_fleet(
    fixture: dict,
    workdir: str,
    ready_file: str,
    stats_file: str,
    log_file: str,
    extra=(),
    env_extra=None,
) -> subprocess.Popen:
    cmd = [
        sys.executable,
        "-m",
        "sheeprl_tpu.serve.fleet",
        f"checkpoint_path={fixture['ckpt']}",
        f"workdir={workdir}",
        f"ready_file={ready_file}",
        f"stats_file={stats_file}",
        *FLEET_OVERRIDES,
        *REPLICA_OVERRIDES,
        *extra,
    ]
    log = open(log_file, "a")
    env = dict(
        os.environ,
        JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
        PYTHONPATH=REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    env.pop("SHEEPRL_TPU_FAILPOINTS", None)  # drills opt in via env_extra
    env.update(env_extra or {})
    return subprocess.Popen(
        cmd,
        cwd=os.path.dirname(fixture["run_dir"]),
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
    )


def read_membership(path: str) -> list:
    try:
        with open(path) as f:
            return json.load(f).get("members", [])
    except (OSError, ValueError):
        return []


# --------------------------------------------------------------------------- load
class PriorityLoadClient(threading.Thread):
    """Closed-loop client with a priority class and a pause gate.

    Same contract as serve_smoke's LoadClient — one outstanding request,
    unique ids, retries the SAME id through backpressure and connection loss —
    plus: every request carries ``priority``, and while ``pause`` is set the
    client goes idle BETWEEN requests (``idle`` flips True only once nothing
    is in flight, so drill phases can quiesce the fleet deterministically)."""

    def __init__(
        self,
        name: str,
        holder: dict,
        obs: dict,
        stop: threading.Event,
        pause: threading.Event,
        priority: int,
        pace_s: float = 0.002,
    ):
        super().__init__(name=name, daemon=True)
        self.client = name
        self.holder = holder
        self.obs = obs
        self.stop_event = stop
        self.pause = pause
        self.priority = int(priority)
        self.pace_s = pace_s
        self.results: dict = {}
        self.unresolved: set = set()
        self.retries = 0
        self.idle = True
        self._sock = None
        self._file = None

    def _disconnect(self) -> None:
        for closable in (self._file, self._sock):
            if closable is not None:
                try:
                    closable.close()
                except OSError:
                    pass
        self._sock = self._file = None

    def _connect(self) -> None:
        if self._sock is None:
            self._sock = socket.create_connection(self.holder["addr"], timeout=10.0)
            self._file = self._sock.makefile("rwb")

    def _resolve(self, rid: str):
        while not self.stop_event.is_set():
            try:
                self._connect()
                payload = {"id": rid, "obs": self.obs, "priority": self.priority}
                self._file.write((json.dumps(payload) + "\n").encode())
                self._file.flush()
                line = self._file.readline()
                if not line:
                    raise ConnectionError("eof")
                resp = json.loads(line)
            except (OSError, ValueError, ConnectionError):
                self._disconnect()
                self.retries += 1
                time.sleep(0.1)
                continue
            if resp.get("status") == "rejected":
                self.retries += 1
                time.sleep(max(resp.get("retry_after_ms", 50.0), 50.0) / 1000.0)
                continue
            return resp
        return None

    def run(self) -> None:
        n = 0
        while not self.stop_event.is_set():
            if self.pause.is_set():
                self.idle = True
                time.sleep(0.02)
                continue
            self.idle = False
            rid = f"{self.client}-{n}"
            self.unresolved.add(rid)
            resp = self._resolve(rid)
            if resp is None:
                break
            self.unresolved.discard(rid)
            self.results[rid] = resp
            n += 1
            time.sleep(self.pace_s)
        self.idle = True
        self._disconnect()


def priority_burst(addr, obs: dict, n_p0: int = 240, n_p1: int = 4) -> dict:
    """Pipeline ``n_p0`` priority-0 then ``n_p1`` priority-1 requests over one
    router connection and collect every terminal response. ``n_p1`` MUST stay
    strictly below one replica queue's depth: then an all-p1 full queue is
    impossible and a correct shed policy can never shed a p1."""
    payloads = [
        {"id": f"burst-p0-{i}", "obs": obs, "priority": 0} for i in range(n_p0)
    ] + [{"id": f"burst-p1-{i}", "obs": obs, "priority": 1} for i in range(n_p1)]
    responses: dict = {}
    with socket.create_connection(addr, timeout=60.0) as sock:
        f = sock.makefile("rwb")

        def reader():
            for _ in range(len(payloads)):
                line = f.readline()
                if not line:
                    return
                resp = json.loads(line)
                responses[resp.get("id")] = resp

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        for p in payloads:
            f.write((json.dumps(p) + "\n").encode())
        f.flush()
        t.join(timeout=120.0)
    missing = [p["id"] for p in payloads if p["id"] not in responses]
    if missing:
        raise SystemExit(f"priority burst lost {len(missing)} responses: {missing[:5]}...")
    return responses


# --------------------------------------------------------------------------- audit
def audit_fleet_stats(stats: dict, label: str) -> None:
    total = stats["Fleet/requests_total"]
    parts = (
        stats["Fleet/ok"]
        + stats["Fleet/shed"]
        + stats["Fleet/rejected"]
        + stats["Fleet/deadline_missed"]
        + stats["Fleet/errors"]
    )
    if total != parts:
        raise SystemExit(
            f"{label}: accounting broken — Fleet/requests_total={total} but terminal sum={parts}"
        )


class TrapListener(threading.Thread):
    """A listening socket that only counts connections — the forged zombie
    membership entry points here, and the count must stay 0."""

    def __init__(self):
        super().__init__(name="fleet-smoke-trap", daemon=True)
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.sock.settimeout(0.2)
        self.port = self.sock.getsockname()[1]
        self.accepts = 0
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self.accepts += 1
            conn.close()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass


# --------------------------------------------------------------------------- drill
def main(workdir: str | None = None, timeout: float = 600.0) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="serve_fleet_smoke_")
    os.makedirs(workdir, exist_ok=True)
    started = time.monotonic()
    fixture = serve_smoke.build_fixture(workdir)

    fleet_dir = os.path.join(workdir, "fleet")
    membership_file = os.path.join(fleet_dir, "membership.json")
    ready_file = os.path.join(workdir, "router_ready.json")
    stats_file = os.path.join(workdir, "fleet_stats.json")
    log_file = os.path.join(workdir, "fleet.log")
    # one-shot canary failure: the FIRST rolling deploy must roll back
    proc = launch_fleet(
        fixture,
        fleet_dir,
        ready_file,
        stats_file,
        log_file,
        env_extra={
            "SHEEPRL_TPU_FAILPOINTS": failpoints.spec_entry(
                "fleet.deploy", "raise", "injected-canary-drill", "hit=1"
            )
        },
    )
    holder = {"addr": None}
    stop = threading.Event()
    pause = threading.Event()
    clients: list = []
    trap = TrapListener()
    try:
        info = serve_smoke.wait_ready(ready_file, proc, log_file, timeout=min(300.0, timeout))
        holder["addr"] = (info["host"], info["port"])

        def router_stats() -> dict:
            return serve_smoke.rpc(holder["addr"], {"op": "stats"})

        members0 = read_membership(membership_file)
        if len(members0) != 3:
            raise SystemExit(f"expected 3 members at boot, membership={members0}")

        clients = [
            PriorityLoadClient(f"c{i}p{p}", holder, fixture["obs"], stop, pause, priority=p)
            for i, p in enumerate([0, 0, 1, 1])
        ]
        for c in clients:
            c.start()

        def ok_count():
            return sum(1 for c in clients for r in c.results.values() if r.get("status") == "ok")

        # phase 1: steady mixed-priority traffic through the router
        serve_smoke._wait_until(lambda: ok_count() >= 30, 90, "30 ok responses via router", log_file)

        # phase 2: priority proof — quiesce the background clients so the p1
        # population is EXACTLY the burst's, then overflow the tiny queues
        pause.set()
        serve_smoke._wait_until(
            lambda: all(c.idle for c in clients), 60, "clients to quiesce for the burst", log_file
        )
        burst = priority_burst(holder["addr"], fixture["obs"], n_p0=240, n_p1=4)
        shed = {rid: r for rid, r in burst.items() if r.get("status") == "shed"}
        if not shed:
            raise SystemExit("priority burst produced no sheds — queues never overflowed")
        p1_shed = [rid for rid in shed if "-p1-" in rid]
        if p1_shed:
            raise SystemExit(f"priority-1 requests were shed before priority-0: {p1_shed}")
        no_hint = [rid for rid, r in shed.items() if "retry_after_ms" not in r]
        if no_hint:
            raise SystemExit(f"shed responses missing the retry_after_ms hint: {no_hint[:5]}")
        errors = [r for r in burst.values() if r.get("status") == "error"]
        if errors:
            raise SystemExit(f"burst saw {len(errors)} errors: {errors[:3]}")
        pause.clear()

        # phase 3: SIGKILL a replica mid-load — failover + supervised respawn
        victim = members0[-1]
        restarts_before = router_stats().get("Fleet/replica_restarts", 0)
        os.kill(victim["pid"], signal.SIGKILL)
        serve_smoke._wait_until(
            lambda: router_stats().get("Fleet/replica_restarts", 0) >= restarts_before + 1,
            120,
            "supervisor to respawn the SIGKILLed replica",
            log_file,
        )
        respawned = [m for m in read_membership(membership_file) if m["slot"] == victim["slot"]]
        if not respawned or respawned[0]["epoch"] <= victim["epoch"]:
            raise SystemExit(
                f"respawned slot {victim['slot']} did not bump its fenced epoch: "
                f"{victim} -> {respawned}"
            )

        # phase 4: rolling certified deploy under load. The injected canary
        # failure forces rollback-then-retry: both counters must move, and the
        # whole fleet must land on the step-200 artifact.
        serve_smoke.write_generation(
            fixture["ckpt_dir"], serve_smoke.perturb(fixture["state"]), step=200
        )
        serve_smoke._wait_until(
            lambda: router_stats().get("Fleet/deploy_rollbacks", 0) >= 1,
            180,
            "injected canary failure to roll the deploy back",
            log_file,
        )
        serve_smoke._wait_until(
            lambda: router_stats().get("Fleet/deploys", 0) >= 1,
            240,
            "rolling deploy to complete on retry",
            log_file,
        )
        members_deployed = read_membership(membership_file)
        stale = [m for m in members_deployed if m.get("step") != 200]
        if len(members_deployed) != 3 or stale:
            raise SystemExit(f"deploy left stale members: {members_deployed}")

        # phase 5: forged zombie write — a stale epoch must answer NOTHING
        trap.start()
        fenced_before = router_stats().get("Fleet/fenced_writes", 0)
        doc = {"members": list(members_deployed)}
        doc["members"].append(
            {
                "slot": members_deployed[0]["slot"],
                "epoch": 0,  # long-fenced generation
                "host": "127.0.0.1",
                "port": trap.port,
                "pid": 0,
            }
        )
        tmp = membership_file + ".forged"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, membership_file)
        serve_smoke._wait_until(
            lambda: router_stats().get("Fleet/fenced_writes", 0) > fenced_before,
            60,
            "router to fence the forged membership write",
            log_file,
        )
        time.sleep(0.5)  # a few more poll cycles: the trap must STAY silent
        if trap.accepts != 0:
            raise SystemExit(
                f"fencing failed: the router dialed the zombie trap {trap.accepts} time(s)"
            )

        # phase 6: audit the live router counters at a quiescent point, then
        # SIGTERM the supervisor with clients back in flight
        pause.set()
        serve_smoke._wait_until(
            lambda: all(c.idle for c in clients), 60, "clients to quiesce for the audit", log_file
        )
        live = router_stats()
        audit_fleet_stats(live, "router live stats")
        if live.get("Fleet/failovers", 0) < 1:
            raise SystemExit(
                f"router never failed over despite the SIGKILL "
                f"(Fleet/failovers={live.get('Fleet/failovers')})"
            )
        pause.clear()
        time.sleep(0.5)  # clients back in flight: the drain happens under load
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=180)
        if rc != 0:
            with open(log_file) as f:
                raise SystemExit(f"fleet exited rc={rc} on SIGTERM; log tail:\n{f.read()[-3000:]}")
    finally:
        stop.set()
        pause.clear()
        trap.stop()
        if proc.poll() is None:
            proc.kill()
    for c in clients:
        c.join(timeout=30)

    # fleet-side audit: clean fleet-wide drain, every FINAL replica drained to
    # rc 0 with sane per-replica counters and zero steady-state retraces
    with open(stats_file) as f:
        fleet_stats = json.load(f)
    if not fleet_stats.get("drained"):
        raise SystemExit(f"fleet did not report a clean drain: {json.dumps(fleet_stats)[:2000]}")
    audit_fleet_stats(fleet_stats, "fleet shutdown stats")
    finals = [r for r in fleet_stats.get("replicas", []) if r.get("final")]
    if len(finals) != 3:
        raise SystemExit(f"expected 3 final replicas, got {len(finals)}")
    for row in finals:
        if row["rc"] != 0:
            raise SystemExit(f"final replica slot={row['slot']} exited rc={row['rc']}")
        rs = row.get("stats") or {}
        serve_smoke._audit_stats(rs, f"replica slot={row['slot']} shutdown stats")
    if fleet_stats.get("Fleet/deploy_rollbacks", 0) < 1 or fleet_stats.get("Fleet/deploys", 0) < 1:
        raise SystemExit(f"deploy counters did not move: {fleet_stats}")
    if fleet_stats.get("Fleet/replica_restarts", 0) < 1:
        raise SystemExit("supervisor never recorded the chaos respawn")

    # client-side audit: zero non-shed losses, zero errors, no p1 ever shed
    unresolved = [rid for c in clients for rid in c.unresolved]
    if any(len(c.unresolved) > 1 for c in clients):
        raise SystemExit(f"non-shed request losses: {unresolved}")
    statuses: dict = {}
    for c in clients:
        for r in c.results.values():
            statuses[r["status"]] = statuses.get(r["status"], 0) + 1
    if statuses.get("error"):
        raise SystemExit(f"clients saw {statuses['error']} error responses: statuses={statuses}")
    p1_client_shed = [
        rid
        for c in clients
        if c.priority == 1
        for rid, r in c.results.items()
        if r.get("status") == "shed"
    ]

    return {
        "workdir": workdir,
        "wall_s": round(time.monotonic() - started, 2),
        "client_statuses": statuses,
        "client_retries": sum(c.retries for c in clients),
        "burst_sheds": len(shed),
        "p1_client_sheds": len(p1_client_shed),
        "fleet_stats": {k: v for k, v in fleet_stats.items() if k.startswith("Fleet/")},
        "unresolved_at_stop": unresolved,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="drill directory (default: fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=600.0, help="overall budget in seconds")
    cli = parser.parse_args()
    result = main(cli.workdir, cli.timeout)
    print(
        "fleet smoke OK: "
        f"{result['client_statuses'].get('ok', 0)} client requests served, "
        f"{result['burst_sheds']} priority-0 sheds (0 priority-1), a mid-load SIGKILL, "
        f"a rolled-back-then-landed rolling deploy, a fenced zombie, "
        f"{result['client_retries']} client retries, zero losses "
        f"({result['wall_s']}s)"
    )
