"""Component-level timing of the DV3-S train step at the bench shape.

Times each phase as its own jit and reports each part's XLA-estimated FLOPs and
achieved MFU, so the slow parts are identified by DATA rather than guesswork.
Fusion across phases is lost in the per-part jits, so the parts need not sum to
the fused step — the point is each part's distance from the roofline.

Every timed window is also recorded as a span in the unified telemetry tracer
(telemetry/trace.py): the closing per-phase table is segmented FROM the
recorded spans (the tracer is the source of truth, not script-local floats),
and the whole run exports as one Chrome/Perfetto trace whose trace id
correlates with any enclosing run's telemetry.

Usage: python scripts/dv3_breakdown.py [batch] [seq] [kernels]

``kernels`` feeds ``algo.world_model.kernels`` (off/auto/pallas/interpret/
reference) — run the script twice (off vs auto) to see what the fused RSSM
step kernels do to the dynamic-scan and world-model fwd+bwd phases.
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_fn
from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
from sheeprl_tpu.config.loader import load_config
from sheeprl_tpu.core.runtime import Runtime
from sheeprl_tpu.telemetry import trace

from bench import _chip_peak_flops  # per-chip bf16 peak table (repo root)

_PEAK = None  # resolved from the live device in main(); NaN MFU on unknown chips
_PHASE = "dv3.phase/"  # span-name prefix the closing table aggregates on


def _fence(out):
    # tunnel-safe fence: reduce ON DEVICE, pull one scalar (block_until_ready
    # returns early on the tunnel; np.asarray of the full leaf would pull GBs)
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(leaf.ravel()[0]))


def _flops(jitted, *args):
    try:
        compiled = jitted.lower(*args).compile()
        cost = compiled.cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        return float(cost.get("flops", 0.0)) or None
    except Exception:
        return None


def timeit(label, fn, *args, iters=10):
    jitted = jax.jit(fn) if not hasattr(fn, "lower") else fn
    fl = _flops(jitted, *args)
    out = jitted(*args)
    _fence(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jitted(*args)
    _fence(out)
    t1 = time.perf_counter()
    dt = (t1 - t0) / iters
    trace.add_span(
        f"{_PHASE}{label}", t0, t1, clock="perf", plane="bench", iters=iters, flops=fl
    )
    mfu = fl / dt / _PEAK if fl else float("nan")
    print(f"{label:>28}: {dt*1e3:8.1f} ms  {fl/1e12 if fl else 0:7.3f} TFLOP  MFU={mfu:6.3f}")
    return dt


def _phase_report():
    """Segment per-phase time from the recorded spans — the tracer's ring is
    the single source of truth for what the script just measured."""
    t = trace.get_tracer()
    if t is None:
        return
    rows = [
        (ev[trace._EV_NAME][len(_PHASE):], ev[trace._EV_DUR] / 1e6, (ev[trace._EV_ARGS] or {}))
        for ev in t.events()
        if ev[trace._EV_PH] == "X" and ev[trace._EV_NAME].startswith(_PHASE)
    ]
    if not rows:
        return
    total = sum(dur for _, dur, _ in rows)
    print(f"\nper-phase share (from {len(rows)} tracer spans, trace {t.trace_id}):")
    for name, dur, args in sorted(rows, key=lambda r: -r[1]):
        iters = int(args.get("iters") or 1)
        print(f"{name:>28}: {dur / iters * 1e3:8.1f} ms/iter  {dur / total * 100:5.1f}% of timed wall")
    print(f"trace exported to: {t.export()}")


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    seq = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    kernels = sys.argv[3] if len(sys.argv) > 3 else "off"
    if trace.get_tracer() is None:
        trace.configure(plane="bench", export_path=f"logs/telemetry/dv3_breakdown_b{batch}.trace.json")
    cfg = load_config(
        overrides=[
            "exp=dreamer_v3",
            "algo=dreamer_v3_S",
            "env=dummy",
            "fabric.precision=bf16-mixed",
            f"algo.per_rank_batch_size={batch}",
            f"algo.per_rank_sequence_length={seq}",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.cnn_keys.decoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.mlp_keys.decoder=[]",
            "algo.imagination_scan_unroll=15",
            f"algo.world_model.kernels={kernels}",
        ]
    )
    runtime = Runtime(accelerator="auto", devices=1, precision=cfg.fabric.precision)
    global _PEAK
    _PEAK = _chip_peak_flops(runtime.device) or float("nan")
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8)})
    actions_dim = (6,)
    modules, params, _ = build_agent(runtime, actions_dim, False, cfg, obs_space)
    rssm = modules.rssm
    rng = np.random.default_rng(0)
    T, B, A = seq, batch, 6

    # ---- FULL fused step FIRST, in a clean HBM state: with the part-timing
    # intermediates alive (~1 GB at batch 128) the fused step degrades to HBM
    # spill-thrash (observed 1.7-3.1 s/step vs the true ~116 ms). A host copy of
    # the params feeds it so donation cannot eat the tree the parts need after.
    host_params = jax.device_get(params)
    init_opt, train_fn = make_train_fn(modules, cfg, runtime, False, actions_dim)
    pr = jax.device_put(host_params)
    opt_states = runtime.replicate(init_opt(pr))
    moments = init_moments()
    batches = {
        "rgb": jax.device_put(rng.integers(0, 255, (1, T, B, 3, 64, 64), dtype=np.uint8)),
        "actions": jax.device_put(rng.random((1, T, B, A), dtype=np.float32)),
        "rewards": jax.device_put(rng.random((1, T, B, 1), dtype=np.float32)),
        "terminated": jax.device_put(np.zeros((1, T, B, 1), np.float32)),
        "truncated": jax.device_put(np.zeros((1, T, B, 1), np.float32)),
        "is_first": jax.device_put(np.zeros((1, T, B, 1), np.float32)),
    }
    key = jax.random.PRNGKey(0)
    state = [pr, opt_states, moments, np.int32(0)]

    def full(batches, key):
        state[0], state[1], state[2], state[3], _flat, m = train_fn(state[0], state[1], state[2], state[3], batches, key)
        return m

    fl = _flops(train_fn, state[0], state[1], state[2], state[3], batches, key)
    for _ in range(2):
        full(batches, key)
    _fence(state[3])
    t0 = time.perf_counter()
    for _ in range(10):
        full(batches, key)
    _fence(state[3])
    t1 = time.perf_counter()
    trace.add_span(
        f"{_PHASE}FULL fused train step", t0, t1, clock="perf", plane="bench", iters=10, flops=fl
    )
    dt = (t1 - t0) / 10
    mfu = fl / dt / _PEAK if fl else float("nan")
    print(f"{'FULL fused train step':>28}: {dt*1e3:8.1f} ms  {fl/1e12 if fl else 0:7.3f} TFLOP  MFU={mfu:6.3f}")
    print("  (NOTE: XLA cost analysis does not scale lax.scan body flops by trip")
    print("   count — the T-step dynamic scan is undercounted (the imagination")
    print("   scan IS counted here because this config fully unrolls it), so the")
    print("   true model-flops MFU is HIGHER than this XLA-estimate figure.)")
    del state, pr, opt_states, moments, batches
    train_fn = None

    # ---- per-part timings (each its own jit; fusion across parts is lost)
    obs = jax.device_put((rng.random((T, B, 3, 64, 64), np.float32) - 0.5).astype(np.float32))
    actions = jax.device_put(rng.random((T, B, A), np.float32).astype(np.float32))
    is_first = jax.device_put(np.zeros((T, B, 1), np.float32))
    key = jax.random.PRNGKey(0)
    wm = params["world_model"]

    enc = jax.jit(lambda p, o: modules.encoder.apply(p["encoder"], {"rgb": o}))
    embedded = enc(wm, obs)
    timeit("encoder fwd", enc, wm, obs)

    dyn = jax.jit(lambda p, e, a, f, k: rssm.dynamic_scan(p, e, a, f, k))
    rs, post, pl, ql = dyn(wm, embedded, actions, is_first, key)
    timeit(f"dynamic_scan fwd (T={T})", dyn, wm, embedded, actions, is_first, key)

    latents = jnp.concatenate([post.reshape(*post.shape[:-2], -1), rs], axis=-1)
    dec = jax.jit(lambda p, z: modules.observation_model.apply(p["observation_model"], z))
    timeit("decoder fwd", dec, wm, latents)

    heads = jax.jit(
        lambda p, z: (
            modules.reward_model.apply(p["reward_model"], z),
            modules.continue_model.apply(p["continue_model"], z),
        )
    )
    timeit("reward+continue heads fwd", heads, wm, latents)

    # world-model fwd+bwd: the reconstruction phase as one value_and_grad
    def wm_loss(p, o, a, f, k):
        e = modules.encoder.apply(p["encoder"], {"rgb": o})
        rs_, post_, _, _ = rssm.dynamic_scan(p, e, a, f, k)
        z = jnp.concatenate([post_.reshape(*post_.shape[:-2], -1), rs_], axis=-1)
        recon = modules.observation_model.apply(p["observation_model"], z)["rgb"]
        rew = modules.reward_model.apply(p["reward_model"], z)
        cont = modules.continue_model.apply(p["continue_model"], z)
        return (
            jnp.mean((recon.astype(jnp.float32) - o) ** 2)
            + jnp.mean(rew.astype(jnp.float32) ** 2)
            + jnp.mean(cont.astype(jnp.float32) ** 2)
        )

    wm_grad = jax.jit(jax.grad(wm_loss))
    timeit("world-model fwd+bwd", wm_grad, wm, obs, actions, is_first, key)

    # imagination: H steps over T*B rows
    start_prior = post.reshape(1, -1, rssm.stoch_state_size)[0]
    start_rec = rs.reshape(1, -1, rs.shape[-1])[0]
    H = int(cfg.algo.horizon)

    def imagine(p, ap, sp, sr, k):
        def step(carry, kk):
            pf, rec = carry
            k1, k2 = jax.random.split(kk)
            prior, rec = rssm.imagination_step(p, pf, rec, jnp.zeros((sp.shape[0], A), jnp.float32), k1)
            return (prior.reshape(pf.shape), rec), prior

        return jax.lax.scan(step, (sp, sr), jax.random.split(k, H), unroll=H)[1]

    timeit(f"imagination scan (H={H} fwd)", jax.jit(imagine), wm, params["actor"], start_prior, start_rec, key)

    _phase_report()


if __name__ == "__main__":
    main()
