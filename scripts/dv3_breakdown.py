"""Component-level timing of the DV3-S train step at the bench shape.

Times each phase as its own jit (fusion across phases is lost, so the parts sum
to more than the fused step — the point is the RATIO between parts).
Usage: python scripts/dv3_breakdown.py [batch] [seq]
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_fn
from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
from sheeprl_tpu.config.loader import load_config
from sheeprl_tpu.core.runtime import Runtime


def _fence(out):
    # tunnel-safe fence: reduce ON DEVICE, pull one scalar (block_until_ready
    # returns early on the tunnel; np.asarray of the full leaf would pull GBs)
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(leaf.ravel()[0]))


def timeit(label, fn, *args, iters=10):
    out = fn(*args)
    _fence(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _fence(out)
    dt = (time.perf_counter() - t0) / iters * 1000
    print(f"{label:>28}: {dt:8.1f} ms")
    return dt


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    seq = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    cfg = load_config(
        overrides=[
            "exp=dreamer_v3",
            "algo=dreamer_v3_S",
            "env=dummy",
            "fabric.precision=bf16-mixed",
            f"algo.per_rank_batch_size={batch}",
            f"algo.per_rank_sequence_length={seq}",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.cnn_keys.decoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.mlp_keys.decoder=[]",
        ]
    )
    runtime = Runtime(accelerator="auto", devices=1, precision=cfg.fabric.precision)
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8)})
    actions_dim = (6,)
    modules, params, _ = build_agent(runtime, actions_dim, False, cfg, obs_space)
    rssm = modules.rssm

    rng = np.random.default_rng(0)
    T, B, A = seq, batch, 6
    obs = jax.device_put((rng.random((T, B, 3, 64, 64), np.float32) - 0.5).astype(np.float32))
    actions = jax.device_put(rng.random((T, B, A), np.float32).astype(np.float32))
    is_first = jax.device_put(np.zeros((T, B, 1), np.float32))
    key = jax.random.PRNGKey(0)
    wm = params["world_model"]

    enc = jax.jit(lambda p, o: modules.encoder.apply(p["encoder"], {"rgb": o}))
    embedded = enc(wm, obs)
    t_enc = timeit("encoder fwd", enc, wm, obs)

    dyn = jax.jit(lambda p, e, a, f, k: rssm.dynamic_scan(p, e, a, f, k))
    rs, post, pl, ql = dyn(wm, embedded, actions, is_first, key)
    t_dyn = timeit("dynamic_scan fwd (T=64)", dyn, wm, embedded, actions, is_first, key)

    latents = jnp.concatenate([post.reshape(*post.shape[:-2], -1), rs], axis=-1)
    dec = jax.jit(lambda p, z: modules.observation_model.apply(p["observation_model"], z))
    t_dec = timeit("decoder fwd", dec, wm, latents)

    heads = jax.jit(
        lambda p, z: (
            modules.reward_model.apply(p["reward_model"], z),
            modules.continue_model.apply(p["continue_model"], z),
        )
    )
    t_heads = timeit("reward+continue heads fwd", heads, wm, latents)

    # imagination: H steps over TB rows
    start_prior = post.reshape(1, -1, rssm.stoch_state_size)[0]
    start_rec = rs.reshape(1, -1, rs.shape[-1])[0]
    H = int(cfg.algo.horizon)

    def imagine(p, ap, sp, sr, k):
        def step(carry, kk):
            pf, rec = carry
            k1, k2 = jax.random.split(kk)
            prior, rec = rssm.imagination_step(p, pf, rec, jnp.zeros((sp.shape[0], A), jnp.float32), k1)
            return (prior.reshape(pf.shape), rec), prior

        return jax.lax.scan(step, (sp, sr), jax.random.split(k, H))[1]

    t_img = timeit("imagination scan (H fwd)", jax.jit(imagine), wm, params["actor"], start_prior, start_rec, key)

    # full fused train step
    init_opt, train_fn = make_train_fn(modules, cfg, runtime, False, actions_dim)
    opt_states = runtime.replicate(init_opt(params))
    pr = runtime.replicate(params)
    moments = init_moments()
    batches = {
        "rgb": jax.device_put(rng.integers(0, 255, (1, T, B, 3, 64, 64), dtype=np.uint8)),
        "actions": jax.device_put(rng.random((1, T, B, A), dtype=np.float32)),
        "rewards": jax.device_put(rng.random((1, T, B, 1), dtype=np.float32)),
        "terminated": jax.device_put(np.zeros((1, T, B, 1), dtype=np.float32)),
        "truncated": jax.device_put(np.zeros((1, T, B, 1), dtype=np.float32)),
        "is_first": jax.device_put(np.zeros((1, T, B, 1), dtype=np.float32)),
    }

    state = [pr, opt_states, moments, np.int32(0)]

    def full(batches, key):
        state[0], state[1], state[2], state[3], m = train_fn(state[0], state[1], state[2], state[3], batches, key)
        return m

    t_full = timeit("FULL fused train step", full, batches, key, iters=10)
    fwd_sum = t_enc + t_dyn + t_dec + t_heads + t_img
    print(f"{'sum of fwd parts':>28}: {fwd_sum:8.1f} ms (full step / fwd-sum = {t_full / fwd_sum:.2f}x)")


if __name__ == "__main__":
    main()
