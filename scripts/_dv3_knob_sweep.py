"""One-off sweep of DV3 precision/unroll knobs at the bench shape (see task log)."""

import sys
import time

sys.path.insert(0, ".")

import gymnasium as gym
import jax
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_fn
from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
from sheeprl_tpu.config.loader import load_config
from sheeprl_tpu.core.runtime import Runtime


def run(label, extra, batch=128):
    cfg = load_config(
        overrides=[
            "exp=dreamer_v3",
            "algo=dreamer_v3_S",
            "env=dummy",
            f"algo.per_rank_batch_size={batch}",
            "algo.per_rank_sequence_length=64",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.cnn_keys.decoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.mlp_keys.decoder=[]",
            *extra,
        ]
    )
    runtime = Runtime(accelerator="auto", devices=1, precision=cfg.fabric.precision)
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8)})
    modules, params, _ = build_agent(runtime, (6,), False, cfg, obs_space)
    init_opt, train_fn = make_train_fn(modules, cfg, runtime, False, (6,))
    opt = runtime.replicate(init_opt(params))
    pr = runtime.replicate(params)
    mom = init_moments()
    cnt = np.int32(0)
    rng = np.random.default_rng(0)
    T, B, A = 64, batch, 6
    batches = {
        "rgb": jax.device_put(rng.integers(0, 255, (1, T, B, 3, 64, 64), dtype=np.uint8)),
        "actions": jax.device_put(rng.random((1, T, B, A), dtype=np.float32)),
        "rewards": jax.device_put(rng.random((1, T, B, 1), dtype=np.float32)),
        "terminated": jax.device_put(np.zeros((1, T, B, 1), np.float32)),
        "truncated": jax.device_put(np.zeros((1, T, B, 1), np.float32)),
        "is_first": jax.device_put(np.zeros((1, T, B, 1), np.float32)),
    }
    key = jax.random.PRNGKey(0)
    try:
        flops = None
        try:
            compiled = train_fn.lower(pr, opt, mom, cnt, batches, key).compile()
            c = compiled.cost_analysis()
            c = c[0] if isinstance(c, (list, tuple)) else c
            flops = float(c.get("flops", 0.0)) or None
        except Exception:
            pass
        for _ in range(2):
            pr, opt, mom, cnt, _flat, m = train_fn(pr, opt, mom, cnt, batches, key)
        np.asarray(cnt)
        t0 = time.perf_counter()
        for _ in range(10):
            pr, opt, mom, cnt, _flat, m = train_fn(pr, opt, mom, cnt, batches, key)
        np.asarray(cnt)
        dt = (time.perf_counter() - t0) / 10
        mfu = flops / dt / 197e12 if flops else float("nan")
        print(f"{label}: {dt*1e3:.1f} ms/step  flops={flops/1e12 if flops else 0:.2f}T  MFU={mfu:.3f}", flush=True)
    except Exception as e:
        print(f"{label}: FAILED {type(e).__name__}: {str(e)[:160]}", flush=True)


if __name__ == "__main__":
    configs = [
        ("bf16-mixed base", ("fabric.precision=bf16-mixed",)),
        ("bf16-mixed d4", ("fabric.precision=bf16-mixed", "algo.world_model.dynamic_scan_unroll=4")),
        ("bf16-mixed i15", ("fabric.precision=bf16-mixed", "algo.imagination_scan_unroll=15")),
        (
            "bf16-mixed d4+i15",
            (
                "fabric.precision=bf16-mixed",
                "algo.world_model.dynamic_scan_unroll=4",
                "algo.imagination_scan_unroll=15",
            ),
        ),
        ("bf16-true base", ("fabric.precision=bf16-true",)),
        (
            "bf16-true d4+i15",
            (
                "fabric.precision=bf16-true",
                "algo.world_model.dynamic_scan_unroll=4",
                "algo.imagination_scan_unroll=15",
            ),
        ),
    ]
    which = sys.argv[1:] or None
    for label, extra in configs:
        if which and not any(w in label for w in which):
            continue
        run(label, extra)
