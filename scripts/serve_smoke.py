#!/usr/bin/env python
"""Serve smoke: chaos-under-load drill for the policy-serving runtime.

1. synthesize a tiny certified PPO checkpoint + sidecar config WITHOUT
   training (compose config, init agent params, save_state, certify);
2. launch ``sheeprl_serve.py`` as a subprocess and drive sustained load from
   concurrent closed-loop clients (unique request ids, retry on backpressure
   and connection loss);
3. mid-load, certify a SECOND checkpoint generation and wait for responses
   stamped with the new generation id — a hot-reload under traffic. Server A
   carries a one-shot ``reload.canary:raise`` failpoint (core/failpoints.py),
   so the first reload attempt deterministically fails its post-swap canary
   and must roll back to generation 1 before the retry succeeds
   (``Serve/reload_rollbacks >= 1`` is asserted at shutdown);
4. SIGTERM the server under load: it must stop admitting (``rejected /
   draining`` — still a response), drain everything admitted, write a final
   stats snapshot, and exit 0;
5. restart the server; its reloader must pick the newest certified generation
   back up and traffic must resume;
6. audit: every request id issued resolved to exactly one terminal status
   (zero non-shed losses), the server-side counters satisfy
   ``requests_total == ok + shed + rejected + deadline_missed + errors`` at
   both shutdowns, and ``Compile/retraces`` stayed 0 — no request mix ever
   retraced after warmup.

Run directly (``python scripts/serve_smoke.py``) or through the registered
tier-1 test (tests/test_utils/test_serve_smoke.py). ``bench.py --target
serve`` reuses :func:`build_fixture`/:func:`launch_server` for its QPS sweep.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from sheeprl_tpu.core import failpoints  # noqa: E402

# Tiny MLP agent on the dummy discrete env: big enough to exercise the real
# build_agent/player path, small enough that boot + 3-bucket AOT warmup is
# seconds on CPU.
FIXTURE_OVERRIDES = [
    "exp=ppo",
    "env=dummy",
    "env.id=discrete_dummy",
    "env.num_envs=1",
    "env.sync_env=True",
    "env.capture_video=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "seed=3",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
]

# Serve knobs for the drill, passed as CLI overrides so the sidecar config
# stays a plain training config (the common production shape).
SERVE_OVERRIDES = [
    "serve.batch.max_size=4",
    "serve.batch.max_wait_ms=4.0",
    "serve.queue.max_depth=64",
    "serve.queue.deadline_ms=30000",
    "serve.reload.poll_s=0.25",
]


# --------------------------------------------------------------------------- fixture
def write_generation(ckpt_dir: str, state: dict, step: int) -> str:
    """Save + certify one checkpoint generation (``ckpt_<step>_0.ckpt``)."""
    from sheeprl_tpu.utils.checkpoint import certify, save_state

    path = os.path.join(ckpt_dir, f"ckpt_{step}_0.ckpt")
    info = save_state(path, state)
    certify(path, crc32=info.get("crc32"), size=info.get("size"), policy_step=step)
    return path


def perturb(state: dict) -> dict:
    """A distinguishable next generation: nudge every float leaf."""
    import jax
    import numpy as np

    def bump(a):
        arr = np.asarray(a)
        if np.issubdtype(arr.dtype, np.floating):
            return arr + np.asarray(0.01, dtype=arr.dtype)
        return a

    return {"agent": jax.tree_util.tree_map(bump, state["agent"])}


def build_fixture(workdir: str) -> dict:
    """Synthesize a servable certified run dir (config sidecar + checkpoint)
    without training — the serve smoke/bench bootstrap."""
    import numpy as np
    import yaml

    from sheeprl_tpu.config import compose
    from sheeprl_tpu.serve.engine import init_agent_state, spaces_from_config

    cfg = compose(config_name="config", overrides=FIXTURE_OVERRIDES)
    state = init_agent_state(cfg)
    obs_space, _, _ = spaces_from_config(cfg)
    obs = {
        k: np.zeros(obs_space[k].shape, dtype=np.float32).tolist()
        for k in list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)
    }
    run_dir = os.path.join(workdir, "run")
    ckpt_dir = os.path.join(run_dir, "checkpoint")
    os.makedirs(ckpt_dir, exist_ok=True)
    with open(os.path.join(run_dir, "config.yaml"), "w") as f:
        yaml.safe_dump(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg), f)
    ckpt = write_generation(ckpt_dir, state, step=100)
    return {"run_dir": run_dir, "ckpt_dir": ckpt_dir, "ckpt": ckpt, "state": state, "obs": obs}


# --------------------------------------------------------------------------- server
def launch_server(
    fixture: dict, ready_file: str, stats_file: str, log_file: str, extra=(), env_extra=None
) -> subprocess.Popen:
    cmd = [
        sys.executable,
        os.path.join(REPO_ROOT, "sheeprl_serve.py"),
        f"checkpoint_path={fixture['ckpt']}",
        f"serve.server.ready_file={ready_file}",
        f"stats_file={stats_file}",
        *SERVE_OVERRIDES,
        *extra,
    ]
    log = open(log_file, "a")
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    env.pop("SHEEPRL_TPU_FAILPOINTS", None)  # drills opt in per server via env_extra
    env.update(env_extra or {})
    return subprocess.Popen(
        cmd,
        cwd=os.path.dirname(fixture["run_dir"]),
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
    )


def wait_ready(ready_file: str, proc: subprocess.Popen, log_file: str, timeout: float) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            with open(log_file) as f:
                tail = f.read()[-2000:]
            raise SystemExit(f"server exited rc={proc.returncode} before ready; log tail:\n{tail}")
        if os.path.isfile(ready_file):
            try:
                with open(ready_file) as f:
                    return json.load(f)
            except ValueError:
                pass  # mid-replace; retry
        time.sleep(0.05)
    raise SystemExit(f"server not ready within {timeout}s (see {log_file})")


def rpc(addr, payload: dict, timeout: float = 10.0) -> dict:
    with socket.create_connection(addr, timeout=timeout) as sock:
        f = sock.makefile("rwb")
        f.write((json.dumps(payload) + "\n").encode())
        f.flush()
        line = f.readline()
    if not line:
        raise ConnectionError("server closed connection")
    return json.loads(line)


# --------------------------------------------------------------------------- load
class LoadClient(threading.Thread):
    """Closed-loop client: one outstanding request, unique monotonically
    numbered ids, retries the SAME id through backpressure (``rejected``) and
    connection loss (kill/restart window) until it gets a terminal answer."""

    def __init__(self, name: str, holder: dict, obs: dict, stop: threading.Event, pace_s: float = 0.002):
        super().__init__(name=name, daemon=True)
        self.client = name
        self.holder = holder
        self.obs = obs
        self.stop_event = stop
        self.pace_s = pace_s
        self.results: dict = {}  # id -> terminal response
        self.unresolved: set = set()
        self.gens: set = set()
        self.retries = 0
        self._sock = None
        self._file = None

    # -- connection management ----------------------------------------------------
    def _disconnect(self) -> None:
        for closable in (self._file, self._sock):
            if closable is not None:
                try:
                    closable.close()
                except OSError:
                    pass
        self._sock = self._file = None

    def _connect(self) -> None:
        if self._sock is not None:
            return
        self._sock = socket.create_connection(self.holder["addr"], timeout=10.0)
        self._file = self._sock.makefile("rwb")

    # -- request loop --------------------------------------------------------------
    def _resolve(self, rid: str):
        """Retry until a TERMINAL response for ``rid`` (or the drill stops)."""
        while not self.stop_event.is_set():
            try:
                self._connect()
                self._file.write((json.dumps({"id": rid, "obs": self.obs}) + "\n").encode())
                self._file.flush()
                line = self._file.readline()
                if not line:
                    raise ConnectionError("eof")
                resp = json.loads(line)
            except (OSError, ValueError, ConnectionError):
                self._disconnect()
                self.retries += 1
                time.sleep(0.1)
                continue
            if resp.get("status") == "rejected":
                # backpressure or draining: still an answer; retry the same id
                self.retries += 1
                time.sleep(max(resp.get("retry_after_ms", 50.0), 50.0) / 1000.0)
                continue
            return resp
        return None

    def run(self) -> None:
        n = 0
        while not self.stop_event.is_set():
            rid = f"{self.client}-{n}"
            self.unresolved.add(rid)
            resp = self._resolve(rid)
            if resp is None:
                break  # drill stopped mid-retry; this id stays in unresolved
            self.unresolved.discard(rid)
            self.results[rid] = resp
            if resp.get("gen") is not None:
                self.gens.add(resp["gen"])
            n += 1
            time.sleep(self.pace_s)
        self._disconnect()


# --------------------------------------------------------------------------- audit
def _audit_stats(stats: dict, label: str) -> None:
    total = stats["Serve/requests_total"]
    parts = (
        stats["Serve/ok"]
        + stats["Serve/shed"]
        + stats["Serve/rejected"]
        + stats["Serve/deadline_missed"]
        + stats["Serve/errors"]
    )
    if total != parts:
        raise SystemExit(f"{label}: accounting broken — requests_total={total} but terminal sum={parts}")
    if stats.get("Compile/retraces", 0) != 0:
        raise SystemExit(f"{label}: {stats['Compile/retraces']} steady-state retraces (must be 0)")


def _wait_until(pred, timeout: float, what: str, log_file: str = None) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    tail = ""
    if log_file and os.path.isfile(log_file):
        with open(log_file) as f:
            tail = "; server log tail:\n" + f.read()[-2000:]
    raise SystemExit(f"timed out after {timeout}s waiting for {what}{tail}")


# --------------------------------------------------------------------------- drill
def main(workdir: str | None = None, timeout: float = 420.0) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="serve_smoke_")
    os.makedirs(workdir, exist_ok=True)
    started = time.monotonic()
    fixture = build_fixture(workdir)

    rf1 = os.path.join(workdir, "ready1.json")
    sf1 = os.path.join(workdir, "stats1.json")
    log1 = os.path.join(workdir, "server1.log")
    # Server A runs with a one-shot canary failpoint: the FIRST hot-reload
    # attempt (the mid-load gen-2 certify below — boot is not a canary
    # evaluation, its artifact is pre-marked loaded) must fail its canary,
    # roll back to gen 1, then succeed on the next poll. Proves the full
    # swap -> canary-fail -> rollback -> retry path under real traffic.
    proc1 = launch_server(
        fixture,
        rf1,
        sf1,
        log1,
        env_extra={
            "SHEEPRL_TPU_FAILPOINTS": failpoints.spec_entry(
                "reload.canary", "raise", "injected-canary-drill", "hit=1"
            )
        },
    )
    holder = {"addr": None}
    try:
        info = wait_ready(rf1, proc1, log1, timeout=min(240.0, timeout))
        holder["addr"] = (info["host"], info["port"])

        stop = threading.Event()
        clients = [LoadClient(f"c{i}", holder, fixture["obs"], stop) for i in range(3)]
        for c in clients:
            c.start()

        def ok_count():
            return sum(1 for c in clients for r in c.results.values() if r.get("status") == "ok")

        # phase 1: steady traffic on the boot generation
        _wait_until(lambda: ok_count() >= 20, 60, "20 ok responses on gen 1", log1)

        # phase 2: certify a second generation mid-load; responses must start
        # carrying gen 2 without any client seeing an error or a dropped id
        write_generation(fixture["ckpt_dir"], perturb(fixture["state"]), step=200)
        _wait_until(lambda: any(2 in c.gens for c in clients), 60, "a response from generation 2", log1)
        reload_latency_s = time.monotonic() - started

        # phase 3: SIGTERM under load — drain, final stats, rc 0
        ok_before_kill = ok_count()
        proc1.send_signal(signal.SIGTERM)
        rc1 = proc1.wait(timeout=90)
        if rc1 != 0:
            with open(log1) as f:
                raise SystemExit(f"server A exited rc={rc1} on SIGTERM; log tail:\n{f.read()[-2000:]}")
        with open(sf1) as f:
            stats1 = json.load(f)
        if not stats1.get("drained"):
            raise SystemExit(f"server A did not report a clean drain: {stats1}")
        _audit_stats(stats1, "server A shutdown stats")
        if stats1.get("Serve/reload_rollbacks", 0) < 1:
            raise SystemExit(
                "server A never rolled back: the injected canary failpoint did not fire "
                f"(Serve/reload_rollbacks={stats1.get('Serve/reload_rollbacks')})"
            )

        # phase 4: restart on the same checkpoint dir; the reloader must catch
        # the step-200 generation back up and traffic must resume
        rf2 = os.path.join(workdir, "ready2.json")
        sf2 = os.path.join(workdir, "stats2.json")
        log2 = os.path.join(workdir, "server2.log")
        proc2 = launch_server(fixture, rf2, sf2, log2)
        try:
            info2 = wait_ready(rf2, proc2, log2, timeout=min(240.0, timeout))
            holder["addr"] = (info2["host"], info2["port"])
            _wait_until(lambda: ok_count() >= ok_before_kill + 15, 90, "15 ok responses after restart", log2)
            _wait_until(
                lambda: rpc(holder["addr"], {"op": "health"}).get("gen", 0) >= 2,
                60,
                "restarted server to hot-reload generation 2",
                log2,
            )

            # phase 5: stop load, audit live counters, graceful shutdown
            stop.set()
            for c in clients:
                c.join(timeout=30)
            stats_live = rpc(holder["addr"], {"op": "stats"})
            _audit_stats(stats_live, "server B live stats")
            proc2.send_signal(signal.SIGTERM)
            rc2 = proc2.wait(timeout=90)
            if rc2 != 0:
                with open(log2) as f:
                    raise SystemExit(f"server B exited rc={rc2} on SIGTERM; log tail:\n{f.read()[-2000:]}")
            with open(sf2) as f:
                stats2 = json.load(f)
            if not stats2.get("drained"):
                raise SystemExit(f"server B did not report a clean drain: {stats2}")
            _audit_stats(stats2, "server B shutdown stats")
        finally:
            if proc2.poll() is None:
                proc2.kill()
    finally:
        stop = locals().get("stop")
        if stop is not None:
            stop.set()
        if proc1.poll() is None:
            proc1.kill()

    # client-side audit: every issued id resolved, except at most the one id
    # per client that was mid-retry when the drill stopped it
    unresolved = [rid for c in clients for rid in c.unresolved]
    if any(len(c.unresolved) > 1 for c in clients):
        raise SystemExit(f"non-shed request losses: {unresolved}")
    statuses: dict = {}
    gens: set = set()
    for c in clients:
        gens |= c.gens
        for r in c.results.values():
            statuses[r["status"]] = statuses.get(r["status"], 0) + 1
    if statuses.get("error"):
        raise SystemExit(f"client saw {statuses['error']} error responses: statuses={statuses}")
    if 1 not in gens or 2 not in gens:
        raise SystemExit(f"expected responses from generations 1 and 2, saw {sorted(gens)}")

    return {
        "workdir": workdir,
        "wall_s": round(time.monotonic() - started, 2),
        "client_statuses": statuses,
        "client_retries": sum(c.retries for c in clients),
        "generations_seen": sorted(gens),
        "reload_latency_s": round(reload_latency_s, 2),
        "serverA_stats": {k: v for k, v in stats1.items() if k.startswith(("Serve/", "Compile/"))},
        "serverB_stats": {k: v for k, v in stats2.items() if k.startswith(("Serve/", "Compile/"))},
        "unresolved_at_stop": unresolved,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="drill directory (default: fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=420.0, help="overall budget in seconds")
    cli = parser.parse_args()
    result = main(cli.workdir, cli.timeout)
    print(
        "serve smoke OK: "
        f"{result['client_statuses'].get('ok', 0)} requests served across generations "
        f"{result['generations_seen']} with a mid-load hot-reload and a kill/restart, "
        f"{result['client_retries']} client retries, zero losses, zero retraces "
        f"({result['wall_s']}s)"
    )
