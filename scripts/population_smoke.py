#!/usr/bin/env python
"""Population smoke: the fleet-level chaos drill for sheeprl_tpu/orchestrate/.

Runs a tiny PPO population (one clean trial + one ChaosEnv trial with a
reward-spike divergence window) on a pool of 2 preemptible slots and proves the
elastic orchestration end-to-end:

1. **controller preemption** — the controller itself is SIGTERMed mid-drill
   (after the trial guards arm); it forwards the signal to every trial, each
   trial writes its emergency checkpoint, the journal records the fleet as
   requeued, and a SECOND controller incarnation resumes from the journal with
   no duplicated or lost trials;
2. **slot preemptions** — the restarted controller injects >= 2 SIGTERM
   preemptions into running trials on a deterministic tick schedule (the
   ``orchestrate.inject`` fire-failpoint, ``every=10`` poll ticks — see
   core/failpoints.py — replacing the old wall-clock spacing race); each
   victim checkpoints, requeues with jittered backoff, and resumes from its
   own newest checkpoint;
3. **divergence -> resow** — the chaos trial's HealthSentinel records a
   divergence verdict in ``health/events.jsonl``; the controller kills the
   trial and resows it from the clean peer's newest *certified* checkpoint
   with perturbed hyperparameters (exploit/explore), recording the edge in
   ``lineage.jsonl``;
4. **clean finish** — every trial ends completed-or-resown (no trial failed,
   none lost), the best-trial lineage is reconstructable, and zero trial
   subprocesses are left orphaned.

Run directly (``python scripts/population_smoke.py``) or through the
registered tier-1 test (tests/test_utils/test_population_smoke.py).
``bench.py --target orchestrate`` reuses :func:`main` and reports the
preemption-recovery latency and resow wall clock from the controller counters.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from sheeprl_tpu.core import failpoints  # noqa: E402

# Mirror of the proven health_smoke PPO-dummy configuration, shrunk for fleet
# duty: policy steps == env steps (rollout 4 x 1 sync env), certified
# checkpoints every 16 steps, and the sentinel tuned so the injected reward
# spike (z ~ 1e6+) is unmistakable against clean early-training drift (z ~ 10).
_BASE_OVERRIDES = [
    "exp=ppo",
    "env=dummy",
    "env.id=discrete_dummy",
    "env.num_envs=1",
    "env.sync_env=True",
    "env.capture_video=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.rollout_steps=4",
    "algo.per_rank_batch_size=2",
    "algo.update_epochs=1",
    "algo.total_steps=256",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.run_test=False",
    "buffer.memmap=False",
    "checkpoint.every=16",
    "checkpoint.save_last=False",
    "health.enabled=True",
    "health.check_every=1",
    "health.divergence.warmup=4",
    "health.divergence.streak=1",
    "health.divergence.z_threshold=50.0",
    "health.divergence.z_clear=20.0",
    "health.stall.enabled=False",
    "health.response.grace_iters=3",
    "health.response.recover_iters=4",
    "health.response.rollback_budget=2",
]

# Gen-0-only environmental fault: rewards x1e6 for env steps [40, 64) — the
# spike lands AFTER the clean peer's first certified checkpoints exist, and a
# resown generation is rescheduled weather-free.
_CHAOS_OVERRIDES = [
    "env.wrapper._target_=sheeprl_tpu.envs.chaos.chaos_dummy_env",
    "env.wrapper.chaos.reward_scale_from=40",
    "env.wrapper.chaos.reward_scale_until=64",
    "env.wrapper.chaos.reward_scale=1e6",
]

_SPEC = {
    "orchestrate": {
        "slots": 2,
        "poll_interval_s": 0.2,
        "trial": {
            "max_preemptions": 8,
            "max_failures": 3,
            "requeue_backoff_base_s": 0.2,
            "requeue_backoff_max_s": 2.0,
        },
        "resow": {
            "enabled": True,
            "max_per_trial": 2,
            "parent_wait_s": 120.0,
            "perturb": {"keys": ["algo.optimizer.lr"], "factors": [0.8, 1.25]},
        },
        "exploit": {"interval_s": 0.0},
        "shutdown": {"drain_timeout_s": 90.0},
    },
    "trials": [
        {
            "key": "a_clean",
            "overrides": _BASE_OVERRIDES + ["seed=7"],
            "hyperparams": {"algo.optimizer.lr": 1e-3},
        },
        {
            "key": "b_chaos",
            "overrides": _BASE_OVERRIDES + ["seed=11"],
            "hyperparams": {"algo.optimizer.lr": 1e-3},
            "chaos_overrides": _CHAOS_OVERRIDES,
        },
    ],
}


def _controller(spec_path: str, state_dir: str, inject: int, spacing: float) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    env.pop("SHEEPRL_TPU_FAILPOINTS", None)
    if inject > 0:
        # Deterministic injection clock: the controller's `orchestrate.inject`
        # fire-failpoint triggers on every 10th eligible poll tick (2s of ticks
        # at poll_interval_s=0.2) instead of racing wall-clock spacing against
        # trial startup — same injection schedule on every run and machine.
        env["SHEEPRL_TPU_FAILPOINTS"] = failpoints.spec_entry(
            "orchestrate.inject", "fire", trigger="every=10"
        )
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "sheeprl_tpu.orchestrate.controller",
            "--spec",
            spec_path,
            "--state-dir",
            state_dir,
            "--inject-preempt",
            str(inject),
            "--inject-spacing-s",
            str(spacing),
        ],
        cwd=REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _ready_files(state_dir: str) -> list:
    found = []
    trials_dir = os.path.join(state_dir, "trials")
    try:
        keys = os.listdir(trials_dir)
    except OSError:
        return found
    for key in keys:
        if os.path.exists(os.path.join(trials_dir, key, ".guard_ready")):
            found.append(key)
    return found


def _journal(state_dir: str) -> dict:
    try:
        with open(os.path.join(state_dir, "journal.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _pid_dead(pid) -> bool:
    if not pid:
        return True
    try:
        os.kill(int(pid), 0)
    except (ProcessLookupError, PermissionError, OSError):
        return True
    return False


def main(
    workdir: str | None = None,
    timeout: float = 900.0,
    inject: int = 2,
    restart_controller: bool = True,
) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="population_smoke_")
    os.makedirs(workdir, exist_ok=True)
    state_dir = os.path.join(workdir, "orchestrate")
    spec_path = os.path.join(workdir, "population.json")
    with open(spec_path, "w") as f:
        json.dump(_SPEC, f, indent=2)
    deadline = time.time() + timeout
    transcript: list = []

    if restart_controller:
        # Phase 1: start the fleet, wait until every slot's trial guard is
        # armed, then preempt the CONTROLLER itself (acceptance criterion:
        # restart resumes from the journal with no duplicated/lost trials).
        proc = _controller(spec_path, state_dir, inject=0, spacing=2.0)
        try:
            while time.time() < deadline:
                if proc.poll() is not None:
                    out = proc.stdout.read()
                    raise SystemExit(
                        f"phase-1 controller exited early (rc={proc.returncode}):\n{out[-3000:]}"
                    )
                if len(_ready_files(state_dir)) >= 2:
                    break
                time.sleep(0.25)
            else:
                raise SystemExit("phase 1: trial guards never armed within the timeout")
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=max(deadline - time.time(), 30.0))
        finally:
            if proc.poll() is None:
                proc.kill()
        phase1_out = proc.stdout.read()
        transcript.append(phase1_out)
        if rc != 0:
            raise SystemExit(f"preempted controller must exit 0, got {rc}:\n{phase1_out[-3000:]}")
        snap = _journal(state_dir)
        states = {t["spec"]["key"]: t["state"] for t in snap.get("trials", [])}
        if sorted(states) != ["a_clean", "b_chaos"]:
            raise SystemExit(f"journal lost/duplicated trials across controller kill: {states}")
        if any(s == "running" for s in states.values()):
            raise SystemExit(f"drained journal still claims running trials: {states}")

    # Phase 2 (or the whole drill): run to completion with injected slot
    # preemptions; the chaos trial must diverge, be killed, and be resown.
    proc = _controller(spec_path, state_dir, inject=inject, spacing=2.0)
    try:
        out, _ = proc.communicate(timeout=max(deadline - time.time(), 60.0))
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        raise SystemExit(f"controller did not finish within the timeout; tail:\n{out[-3000:]}")
    transcript.append(out)
    if proc.returncode != 0:
        raise SystemExit(f"controller exited rc={proc.returncode}; tail:\n{out[-3000:]}")
    result_line = next(
        (line for line in reversed(out.splitlines()) if line.startswith("ORCHESTRATE_RESULT ")), None
    )
    if result_line is None:
        raise SystemExit(f"no ORCHESTRATE_RESULT line; tail:\n{out[-3000:]}")
    summary = json.loads(result_line.split("ORCHESTRATE_RESULT ", 1)[1])
    if summary["status"] != "done":
        raise SystemExit(f"fleet did not finish: {summary}")

    # Every trial completed-or-resown: a resown trial ends COMPLETED with
    # generation >= 1; FAILED or still-queued trials mean the drill is broken.
    for key, info in summary["trials"].items():
        if info["state"] != "completed":
            raise SystemExit(f"trial {key} ended {info['state']}, not completed: {summary}")
    counters = summary["counters"]
    if counters["injections"] < inject:
        raise SystemExit(f"only {counters['injections']}/{inject} preemptions were injected")
    if restart_controller and counters["controller_incarnations"] < 2:
        raise SystemExit(f"controller restart did not happen: {counters}")

    # Divergence -> resow from a peer's CERTIFIED checkpoint, recorded in lineage.
    lineage_path = os.path.join(state_dir, "lineage.jsonl")
    with open(lineage_path) as f:
        edges = [json.loads(line) for line in f if line.strip()]
    resows = [e for e in edges if e["kind"] == "resow" and e.get("parent")]
    if not resows:
        raise SystemExit(f"no resow edge in lineage; kinds={[e['kind'] for e in edges]}")
    resow = resows[0]
    if resow["trial"] != "b_chaos" or resow["parent"] != "a_clean":
        raise SystemExit(f"unexpected resow edge: {resow}")
    if not resow.get("ckpt") or not os.path.exists(resow["ckpt"] + ".certified.json"):
        raise SystemExit(f"resow did not come from a certified peer checkpoint: {resow}")
    if summary["trials"]["b_chaos"]["generation"] < 1:
        raise SystemExit("diverged trial was not resown into a new generation")
    seeds = [e for e in edges if e["kind"] == "seed"]
    if len(seeds) != 2:
        raise SystemExit(f"expected exactly one seed edge per trial, got {len(seeds)}")

    # Zero orphaned slots: the journal's final snapshot has no running trials
    # and every recorded pid is dead.
    snap = _journal(state_dir)
    for t in snap.get("trials", []):
        if t["state"] == "running" or not _pid_dead(t.get("pid")):
            raise SystemExit(f"orphaned trial slot: {t['spec']['key']} state={t['state']} pid={t.get('pid')}")

    recoveries = [r["latency_s"] for r in counters.get("preempt_recoveries", [])]
    resow_walls = [r["wall_s"] for r in counters.get("resow_walls", [])]
    return {
        "workdir": workdir,
        "state_dir": state_dir,
        "trials": summary["trials"],
        "injections": counters["injections"],
        "controller_incarnations": counters["controller_incarnations"],
        "resow_edges": len(resows),
        "preempt_recovery_latencies_s": recoveries,
        "preempt_recovery_latency_s": round(sorted(recoveries)[len(recoveries) // 2], 3) if recoveries else None,
        "resow_wall_s": round(resow_walls[0], 3) if resow_walls else None,
        "lineage": lineage_path,
        "transcript_tail": transcript[-1][-800:],
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="drill directory (default: fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=900.0, help="whole-drill timeout in seconds")
    parser.add_argument("--inject", type=int, default=2, help="slot preemptions to inject (phase 2)")
    parser.add_argument(
        "--skip-restart-phase",
        action="store_true",
        help="skip the controller-kill-and-restart phase (single-phase drill)",
    )
    cli = parser.parse_args()
    result = main(
        cli.workdir, cli.timeout, inject=cli.inject, restart_controller=not cli.skip_restart_phase
    )
    print(
        "population smoke OK: "
        f"{result['injections']} injected preemptions survived "
        f"(median recovery {result['preempt_recovery_latency_s']}s), "
        f"diverged trial resown from certified peer in {result['resow_wall_s']}s, "
        f"{result['controller_incarnations']} controller incarnation(s), "
        f"lineage at {result['lineage']}"
    )
