"""Wall-clock benchmark harness (reference benchmarks/benchmark.py:1-52).

Runs one of the ``exp=*_benchmarks`` recipes through the real CLI with
test/logging/checkpointing disabled and prints one JSON line with the elapsed
time, throughput, and the reference's published wall-clock anchor
(README.md:99-176 of the reference; see BASELINE.md).

Usage:
    python benchmarks/benchmark.py ppo
    python benchmarks/benchmark.py dreamer_v3 fabric.devices=1 env.num_envs=4
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Reference wall-clock anchors: (seconds on 4 CPUs, policy steps of the REFERENCE run)
REFERENCE = {
    "ppo": (81.27, 65536),
    "a2c": (84.76, 65536),
    "sac": (320.21, 65536),
    "dreamer_v1": (2207.13, 65536),
    "dreamer_v2": (906.42, 65536),
    "dreamer_v3": (1589.30, 65536),
}


def main() -> None:
    if len(sys.argv) < 2 or sys.argv[1] not in REFERENCE:
        print(f"usage: python benchmarks/benchmark.py <{'|'.join(REFERENCE)}> [overrides...]")
        raise SystemExit(2)
    algo = sys.argv[1]
    overrides = [f"exp={algo}_benchmarks", *sys.argv[2:]]

    from sheeprl_tpu.cli import run
    from sheeprl_tpu.config import compose

    # the recipe (or an override) may run fewer steps than the reference anchor:
    # compare throughputs, not raw wall-clocks, and report both step counts
    run_steps = int(compose(overrides=overrides).algo.total_steps)

    tic = time.perf_counter()
    run(overrides=overrides)
    elapsed = time.perf_counter() - tic

    ref_seconds, ref_steps = REFERENCE[algo]
    sps = run_steps / elapsed
    ref_sps = ref_steps / ref_seconds
    print(
        json.dumps(
            {
                "algo": algo,
                "seconds": round(elapsed, 2),
                "total_steps": run_steps,
                "env_steps_per_sec": round(sps, 2),
                "reference_env_steps_per_sec": round(ref_sps, 2),
                "speedup_vs_reference": round(sps / ref_sps, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
