"""Stable-Baselines3 comparison harness (reference benchmarks/benchmark_sb3.py:1).

Times SB3 on the SAME workloads as this repo's benchmarks so the two frameworks
can be compared on one machine:

    python benchmarks/benchmark_sb3.py ppo   # CartPole-v1, 65_536 steps (cpu)
    python benchmarks/benchmark_sb3.py a2c   # CartPole-v1, 65_536 steps (cpu)
    python benchmarks/benchmark_sb3.py sac   # LunarLanderContinuous, 65_536 steps

Prints one JSON line: {"algo", "sb3_seconds", "env_steps_per_sec", "eval_reward"}.
The companion numbers come from `benchmarks/benchmark.py` / root `bench.py`
(which anchor against the reference's published table when SB3 is absent —
stable_baselines3 is an optional dependency and not part of the baked image).
"""

from __future__ import annotations

import json
import sys
import time

TOTAL_STEPS = 1024 * 64

try:
    import stable_baselines3 as sb3
    from stable_baselines3 import A2C, PPO, SAC
except ImportError:
    print(
        json.dumps(
            {
                "error": "stable_baselines3 is not installed; `pip install stable-baselines3` "
                "to run the head-to-head comparison. The reference's published numbers "
                "(SB3 v2.2.1 on 4 CPUs) are recorded in BASELINE.md: PPO 77.21s, "
                "A2C 84.22s, SAC 336.06s for the same workloads."
            }
        )
    )
    sys.exit(0)

import gymnasium as gym  # noqa: E402


def bench(algo: str) -> dict:
    t0 = time.perf_counter()
    if algo == "ppo":
        env = gym.make("CartPole-v1", render_mode="rgb_array")
        model = PPO("MlpPolicy", env, verbose=0, device="cpu", n_steps=128)
    elif algo == "a2c":
        env = gym.make("CartPole-v1", render_mode="rgb_array")
        model = A2C("MlpPolicy", env, verbose=0, device="cpu", vf_coef=1.0)
    elif algo == "sac":
        env = gym.make("LunarLanderContinuous-v3", render_mode="rgb_array")
        model = SAC("MlpPolicy", env, verbose=0, device="cpu")
    else:
        raise SystemExit(f"unknown algo '{algo}'; choose ppo|a2c|sac")
    model.learn(total_timesteps=TOTAL_STEPS, log_interval=None)
    elapsed = time.perf_counter() - t0
    mean_rew, _ = sb3.common.evaluation.evaluate_policy(model.policy, env)
    return {
        "algo": algo,
        "sb3_seconds": round(elapsed, 2),
        "env_steps_per_sec": round(TOTAL_STEPS / elapsed, 2),
        "eval_reward": round(float(mean_rew), 2),
    }


if __name__ == "__main__":
    print(json.dumps(bench(sys.argv[1] if len(sys.argv) > 1 else "ppo")))
