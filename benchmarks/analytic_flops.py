"""Analytic model-FLOP count for one DreamerV3 gradient step.

``bench.py`` reports MFU from XLA's ``compiled.cost_analysis()``, but XLA counts
a ``lax.scan`` body ONCE instead of multiplying by its trip count (measured in
benchmarks/DV3_MFU_NOTES.md), so the XLA figure undercounts the T=64 dynamic
scan and H=15 imagination scan. This module hand-counts matmul/conv FLOPs from
the config shapes (the MXU work; vector ops are noise at these shapes) so the
bench JSON can carry an honest ``dv3_mfu_analytic`` next to the XLA estimate.

Counting rules (standard practice, e.g. the palm/chinchilla appendix math):
- one matmul [m,k]@[k,n] = 2*m*k*n FLOPs; a conv = 2 * prod(out_spatial) *
  C_out * C_in * k_h * k_w per sample;
- backward = 2x forward for every path that receives parameter gradients
  (so trained paths cost 3x forward; no-grad paths 1x);
- DreamerV3 trains with a REINFORCE actor objective (discrete heads, the bench
  shape), so the imagination rollout's world-model applications are forward-only
  (gradients reach only the actor's own forward, reference dreamer_v3.py:296-320);
- LayerNorms/activations/softmaxes are ignored (<1% of MXU work).

Reference step semantics: dynamic learning over [T,B] then imagination over
H x (T*B) starts (reference sheeprl/algos/dreamer_v3/dreamer_v3.py:48-353).
"""

from __future__ import annotations

from typing import Dict, Sequence


def _mm(m: float, k: float, n: float) -> float:
    return 2.0 * m * k * n


def _mlp(n_samples: float, in_dim: int, hidden: Sequence[int], out_dim: int) -> float:
    dims = [in_dim, *hidden, out_dim]
    return sum(_mm(n_samples, a, b) for a, b in zip(dims[:-1], dims[1:]))


def _encoder_convs(n_samples: float, in_ch: int, mult: int, image: int = 64, stages: int = 4, k: int = 4) -> float:
    """Stride-2 conv stack: image -> image/2**stages (agent.py CNNEncoder)."""
    flops = 0.0
    c_in, side = in_ch, image
    for i in range(stages):
        c_out = (2**i) * mult
        side //= 2
        flops += _mm(n_samples * side * side, c_in * k * k, c_out)  # = 2*out*cin*k*k*cout
        c_in = c_out
    return flops


def _decoder_convs(n_samples: float, out_ch: int, mult: int, image: int = 64, stages: int = 4, k: int = 4) -> float:
    """Mirror transposed-conv stack 4x4 -> image (agent.py CNNDecoder).

    A stride-2 transposed conv [C_in, s, s] -> [C_out, 2s, 2s] costs the same
    matmul volume as the forward conv of the mirrored shape: 2 * (2s)^2/4*k*k...
    counted here as 2 * out_spatial * C_out * C_in * k*k / stride^2 aggregated
    via the input spatial extent (each input pixel drives k*k*C_in*C_out MACs).
    """
    flops = 0.0
    side = image // (2**stages)
    c_in = (2 ** (stages - 1)) * mult
    channels = [(2**i) * mult for i in reversed(range(stages - 1))] + [out_ch]
    for c_out in channels:
        flops += _mm(n_samples * side * side, c_in * k * k, c_out)
        side *= 2
        c_in = c_out
    return flops


def dv3_step_flops(cfg, batch: int, seq: int, actions_dim: Sequence[int], image: int = 64) -> Dict[str, float]:
    """Analytic FLOPs for ONE DreamerV3 gradient step at the given shape.

    Returns a per-part breakdown plus the ``total``; shapes are read from the
    same config tree build_agent consumes.
    """
    wm = cfg.algo.world_model
    mult = int(wm.encoder.cnn_channels_multiplier)
    deter = int(wm.recurrent_model.recurrent_state_size)
    stoch = int(wm.stochastic_size) * int(wm.discrete_size)
    dense = int(cfg.algo.dense_units)
    layers = int(cfg.algo.mlp_layers)
    horizon = int(cfg.algo.horizon)
    stages = 4
    embed = (2 ** (stages - 1)) * mult * (image // 2**stages) ** 2
    latent = deter + stoch
    n_act = int(sum(actions_dim))
    bins = int(wm.reward_model.get("bins", 255))  # critic.bins matches by config contract

    N = float(batch * seq)  # dynamic-phase samples
    M = float(batch * seq)  # imagination lanes
    H = float(horizon)

    def recurrent(n):
        # input MLP (stoch+act -> dense) + fused LayerNorm-GRU ([feat,h] -> 3*deter)
        return _mm(n, stoch + n_act, dense) + _mm(n, dense + deter, 3 * deter)

    def transition(n):
        return _mlp(n, deter, [int(wm.transition_model.hidden_size)], stoch)

    def representation(n):
        return _mlp(n, deter + embed, [int(wm.representation_model.hidden_size)], stoch)

    def head(n, out_dim):
        return _mlp(n, latent, [dense] * layers, out_dim)

    parts: Dict[str, float] = {}
    # ---- dynamic learning: everything here gets world-model gradients (x3)
    parts["encoder"] = 3 * _encoder_convs(N, 3, mult, image, stages)
    parts["dynamic_scan"] = 3 * (recurrent(N) + transition(N) + representation(N))
    parts["decoder"] = 3 * (_mm(N, latent, embed) + _decoder_convs(N, 3, mult, image, stages))
    parts["reward_head"] = 3 * head(N, bins)
    parts["continue_head"] = 3 * head(N, 1)
    # ---- imagination: REINFORCE actor -> world-model rollout is forward-only,
    # the actor forward is trained (x3)
    parts["imagination_rollout"] = H * (recurrent(M) + transition(M))
    parts["imagination_actor"] = 3 * H * _mlp(M, latent, [dense] * layers, n_act)
    # reward, online-critic value, and continue predictions over the imagined
    # trajectories for the lambda targets (no grad)
    parts["imagination_heads"] = head(H * M, bins) + head(H * M, bins) + head(H * M, 1)
    # ---- critic update: trained forward+backward over [H, M], target critic fwd
    parts["critic_update"] = 3 * head(H * M, bins)
    parts["target_critic"] = head(H * M, bins)
    total = sum(parts.values())
    parts["total"] = total
    return parts
