"""Fused Pallas RSSM step kernels (``sheeprl_tpu/ops/pallas/rssm_step.py``).

The contract under test, at two environment-shaped sizes (a CartPole-ish small
config and a walker_walk-ish one):

* ``interpret`` (the Pallas kernel run through the interpreter) is BITWISE
  equal to ``reference`` (the same fused formulation in plain jnp) — the CPU
  proof that the kernel body computes the reference math.
* the hand-written ``custom_vjp`` matches autodiff of the same forward
  (tight in f32, atol-tiered for bf16 — the backward recompute re-rounds).
* dispatch: ``kernels=off`` is the untouched flax path, the
  ``train.kernel_dispatch`` failpoint degrades the fused path to output
  bitwise equal to flax, the VMEM gate falls back rather than crashing, and
  unsupported parameter structures raise :class:`KernelUnsupported`.
* a warmed fused scan dispatches with zero host transfers
  (``jax.transfer_guard``): nothing in the fused path smuggles a Python
  scalar or host constant into the steady-state step.
"""

import jax
import jax.numpy as jnp
import pytest

from sheeprl_tpu.algos.dreamer_v3.agent import MLPWithHead, RecurrentModel, RSSM
from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.core import failpoints
from sheeprl_tpu.ops.pallas import rssm_step as K

pytestmark = pytest.mark.kernels

# env-shaped dims (scaled to CPU-test size; ratios mirror the real configs)
SHAPES = {
    "cartpole": dict(A=2, E=16, DU=24, R=32, HT=20, HR=28, S=4, D=6),
    "walker_walk": dict(A=6, E=64, DU=48, R=64, HT=48, HR=48, S=8, D=8),
}


def _spec(dims, dtype="float32", impl="reference"):
    return K.RSSMStepSpec(
        action_size=dims["A"],
        embed_size=dims["E"],
        dense_units=dims["DU"],
        recurrent_size=dims["R"],
        trans_hidden=dims["HT"],
        repr_hidden=dims["HR"],
        stochastic=dims["S"],
        discrete=dims["D"],
        unimix=0.01,
        eps_in=1e-3,
        eps_gru=1e-3,
        eps_trans=1e-3,
        eps_repr=1e-3,
        dtype=dtype,
        impl=impl,
    )


def _raw_params(dims, key):
    A, E, DU, R = dims["A"], dims["E"], dims["DU"], dims["R"]
    HT, HR, SD = dims["HT"], dims["HR"], dims["S"] * dims["D"]
    ks = jax.random.split(key, 13)
    f32 = jnp.float32
    return {
        "wi_z": jax.random.normal(ks[0], (SD, DU), f32) * 0.1,
        "wi_a": jax.random.normal(ks[1], (A, DU), f32) * 0.1,
        "ln_i_scale": jnp.ones((DU,), f32) + 0.05 * jax.random.normal(ks[2], (DU,)),
        "ln_i_bias": 0.05 * jax.random.normal(ks[3], (DU,)),
        "wg_h": jax.random.normal(ks[4], (R, 3 * R), f32) * 0.1,
        "wg_f": jax.random.normal(ks[5], (DU, 3 * R), f32) * 0.1,
        "ln_g_scale": jnp.ones((3 * R,), f32),
        "ln_g_bias": jnp.zeros((3 * R,), f32),
        "wt": jax.random.normal(ks[6], (R, HT), f32) * 0.1,
        "ln_t_scale": jnp.ones((HT,), f32),
        "ln_t_bias": jnp.zeros((HT,), f32),
        "wt_head": jax.random.normal(ks[7], (HT, SD), f32) * 0.1,
        "bt_head": 0.01 * jax.random.normal(ks[8], (SD,)),
        "wr_h": jax.random.normal(ks[9], (R, HR), f32) * 0.1,
        "wr_e": jax.random.normal(ks[10], (E, HR), f32) * 0.1,
        "ln_r_scale": jnp.ones((HR,), f32),
        "ln_r_bias": jnp.zeros((HR,), f32),
        "wr_head": jax.random.normal(ks[11], (HR, SD), f32) * 0.1,
        "br_head": 0.01 * jax.random.normal(ks[12], (SD,)),
    }


def _scan_data(dims, key, T=5, B=3):
    ks = jax.random.split(key, 5)
    f32 = jnp.float32
    init_raw = jax.random.normal(ks[0], (dims["R"],), f32) * 0.3
    emb = jax.random.normal(ks[1], (T, B, dims["E"]), f32)
    act = jax.random.normal(ks[2], (T, B, dims["A"]), f32)
    isf = (jax.random.uniform(ks[3], (T, B, 1)) < 0.3).astype(f32).at[0].set(1.0)
    return init_raw, emb, act, isf, ks[4]


def _rel_err(tree_a, tree_b):
    worst = 0.0
    for x, y in zip(jax.tree_util.tree_leaves(tree_a), jax.tree_util.tree_leaves(tree_b)):
        x32, y32 = x.astype(jnp.float32), y.astype(jnp.float32)
        d = float(jnp.max(jnp.abs(x32 - y32)))
        worst = max(worst, d / (float(jnp.max(jnp.abs(y32))) + 1e-8))
    return worst


# --------------------------------------------------------------------------- #
# bit-parity: interpret kernel vs reference formulation
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("shape", sorted(SHAPES))
def test_dynamic_scan_interpret_is_bitwise_vs_reference(shape):
    dims = SHAPES[shape]
    spec = _spec(dims)
    p = _raw_params(dims, jax.random.PRNGKey(0))
    init_raw, emb, act, isf, skey = _scan_data(dims, jax.random.PRNGKey(1))
    ref = K.fused_dynamic_scan(p, spec, init_raw, emb, act, isf, skey)
    itp = K.fused_dynamic_scan(p, spec.with_impl("interpret"), init_raw, emb, act, isf, skey)
    for name, r, i in zip(("h", "z", "prior_logits", "post_logits"), ref, itp):
        assert bool(jnp.all(r == i)), f"{name} not bitwise between interpret and reference"


@pytest.mark.parametrize("shape", sorted(SHAPES))
def test_imagination_step_interpret_is_bitwise_vs_reference(shape):
    dims = SHAPES[shape]
    spec = _spec(dims)
    p = _raw_params(dims, jax.random.PRNGKey(2))
    B, SD = 4, dims["S"] * dims["D"]
    ks = jax.random.split(jax.random.PRNGKey(3), 4)
    h = jax.random.normal(ks[0], (B, dims["R"]), jnp.float32) * 0.2
    z = jax.nn.one_hot(
        jax.random.randint(ks[1], (B, dims["S"]), 0, dims["D"]), dims["D"]
    ).reshape(B, SD)
    a = jax.random.normal(ks[2], (B, dims["A"]), jnp.float32)
    # jit both sides: eager dispatch and compiled code differ by FMA fusion
    o_ref = jax.jit(lambda: K.fused_imagination_step(p, spec, z, h, a, ks[3]))()
    o_itp = jax.jit(lambda: K.fused_imagination_step(p, spec.with_impl("interpret"), z, h, a, ks[3]))()
    assert bool(jnp.all(o_ref[0] == o_itp[0]))
    assert bool(jnp.all(o_ref[1] == o_itp[1]))
    assert o_ref[0].shape == (B, SD)  # flat prior, the flax contract


# --------------------------------------------------------------------------- #
# gradient parity: hand-written custom_vjp vs autodiff of the same forward
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize(
    "dtype,tol",
    [
        ("float32", 1e-4),
        # bf16 movement re-rounds the backward recompute; the f32 islands keep
        # the error bounded but not tight
        ("bfloat16", 5e-2),
    ],
)
@pytest.mark.parametrize("shape", sorted(SHAPES))
def test_dynamic_scan_grad_parity(shape, dtype, tol):
    dims = SHAPES[shape]
    spec = _spec(dims, dtype=dtype)
    p = _raw_params(dims, jax.random.PRNGKey(4))
    init_raw, emb, act, isf, skey = _scan_data(dims, jax.random.PRNGKey(5))
    Dn = dims["D"]

    def loss(pp, ir, use_custom_vjp):
        h, z, pl, ql = K.fused_dynamic_scan(
            pp, spec, ir, emb, act, isf, skey, use_custom_vjp=use_custom_vjp
        )
        h, z = h.astype(jnp.float32), z.astype(jnp.float32)
        pl, ql = pl.astype(jnp.float32), ql.astype(jnp.float32)
        return (
            jnp.sum(h * h) * 0.1
            + jnp.sum(z * jnp.arange(Dn, dtype=jnp.float32))
            + jnp.sum(jax.nn.softmax(pl) * ql)
            + jnp.sum(pl * 0.01)
        )

    g_custom = jax.grad(loss, argnums=(0, 1))(p, init_raw, True)
    g_auto = jax.grad(loss, argnums=(0, 1))(p, init_raw, False)
    assert _rel_err(g_custom, g_auto) < tol


def test_imagination_grad_parity():
    dims = SHAPES["walker_walk"]
    spec = _spec(dims)
    p = _raw_params(dims, jax.random.PRNGKey(6))
    B, S, Dn = 3, dims["S"], dims["D"]
    SD = S * Dn
    ks = jax.random.split(jax.random.PRNGKey(7), 4)
    h = jax.random.normal(ks[0], (B, dims["R"]), jnp.float32) * 0.2
    z = jax.nn.one_hot(jax.random.randint(ks[1], (B, S), 0, Dn), Dn).reshape(B, SD)
    a = jax.random.normal(ks[2], (B, dims["A"]), jnp.float32)
    ik = ks[3]

    def loss_custom(pp, hh):
        zp, hn = K.fused_imagination_step(pp, spec, z, hh, a, ik)
        return jnp.sum(hn * hn) + jnp.sum(zp * 0.3)

    def loss_auto(pp, hh):
        (hn, zn), _ = K._imag_math(pp, spec, hh, z, a, jax.random.gumbel(ik, (B, S, Dn), jnp.float32))
        return jnp.sum(hn * hn) + jnp.sum(zn.reshape(B, SD) * 0.3)

    g1 = jax.grad(loss_custom, argnums=(0, 1))(p, h)
    g2 = jax.grad(loss_auto, argnums=(0, 1))(p, h)
    assert _rel_err(g1, g2) < 1e-4


# --------------------------------------------------------------------------- #
# flax parity + dispatch through RSSM
# --------------------------------------------------------------------------- #


def _flax_rssm(dims, kernels):
    rm = RecurrentModel(
        input_size=dims["A"] + dims["S"] * dims["D"],
        recurrent_state_size=dims["R"],
        dense_units=dims["DU"],
        layer_norm=True,
        layer_norm_eps=1e-3,
    )
    rep = MLPWithHead(
        input_dim=dims["E"] + dims["R"],
        hidden_sizes=[dims["HR"]],
        output_dim=dims["S"] * dims["D"],
        activation="silu",
        layer_norm=True,
        layer_norm_eps=1e-3,
    )
    trans = MLPWithHead(
        input_dim=dims["R"],
        hidden_sizes=[dims["HT"]],
        output_dim=dims["S"] * dims["D"],
        activation="silu",
        layer_norm=True,
        layer_norm_eps=1e-3,
    )
    return RSSM(
        rm, rep, trans, stochastic_size=dims["S"], discrete_size=dims["D"],
        unimix=0.01, kernels=kernels,
    )


def _flax_params(rssm, dims, key):
    B = 3
    k1, k2, k3, k4 = jax.random.split(key, 4)
    SD = dims["S"] * dims["D"]
    return {
        "recurrent_model": rssm.recurrent_model.init(
            k1, jnp.zeros((B, dims["A"] + SD)), jnp.zeros((B, dims["R"]))
        ),
        "representation_model": rssm.representation_model.init(
            k2, jnp.zeros((B, dims["E"] + dims["R"]))
        ),
        "transition_model": rssm.transition_model.init(k3, jnp.zeros((B, dims["R"]))),
        "initial_recurrent_state": 0.3 * jax.random.normal(k4, (dims["R"],)),
    }


@pytest.mark.parametrize("shape", sorted(SHAPES))
def test_fused_step_math_matches_flax_single_step(shape):
    """Given identical inputs, one fused step reproduces flax's dynamic_step to
    float rounding (the scan trajectories then diverge only through sampling)."""
    dims = SHAPES[shape]
    SD = dims["S"] * dims["D"]
    rssm = _flax_rssm(dims, "off")
    wm_params = _flax_params(rssm, dims, jax.random.PRNGKey(8))
    B = 3
    ks = jax.random.split(jax.random.PRNGKey(9), 6)
    h_in = jax.random.normal(ks[0], (B, dims["R"])) * 0.2
    z_in = jax.nn.one_hot(
        jax.random.randint(ks[1], (B, dims["S"]), 0, dims["D"]), dims["D"]
    ).reshape(B, SD)
    a = jax.random.normal(ks[2], (B, dims["A"]))
    e = jax.random.normal(ks[3], (B, dims["E"]))
    f = jnp.zeros((B, 1))
    fh, _, _, fpost_l, fprior_l = rssm.dynamic_step(wm_params, z_in, h_in, a, e, f, ks[4])

    spec = _flax_rssm(dims, "reference")._fused_spec(dims["E"], dims["A"])
    p = K.extract_step_params(wm_params, SD)
    ih, iz = K.initial_step_states(p, spec, wm_params["initial_recurrent_state"], B)
    g = jax.random.gumbel(ks[5], (B, dims["S"], dims["D"]), jnp.float32)
    (mh, _, mpost_l, mprior_l), _ = K._dyn_math(p, spec, ih, iz, h_in, z_in, a, e, f, g)

    def _close(x, y):
        return float(jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32)))) < 2e-5

    assert _close(fh, mh)
    assert _close(fprior_l.reshape(B, dims["S"], dims["D"]), mprior_l)
    assert _close(fpost_l.reshape(B, dims["S"], dims["D"]), mpost_l)
    # hoisted initial states: h is bitwise (same tanh), z is one softmax apart
    fih, fiz = rssm.initial_states(wm_params, (B,))
    assert bool(jnp.all(fih == ih))
    assert _close(fiz, iz)


def test_kernels_off_is_the_untouched_flax_path():
    """``kernels=off`` must route through flax code only — outputs at every
    shape match a dispatch-free RSSM bitwise (the seed-behavior guarantee)."""
    dims = SHAPES["cartpole"]
    rssm_off = _flax_rssm(dims, "off")
    wm_params = _flax_params(rssm_off, dims, jax.random.PRNGKey(10))
    init_raw, emb, act, isf, skey = _scan_data(dims, jax.random.PRNGKey(11))
    out_off = rssm_off.dynamic_scan(wm_params, emb, act, isf, skey)
    out_ref = _flax_rssm(dims, "reference").dynamic_scan(wm_params, emb, act, isf, skey)
    # same contract (shapes/dtypes), different sampling streams
    for a_, b_ in zip(out_off, out_ref):
        assert a_.shape == b_.shape and a_.dtype == b_.dtype


def test_kernel_dispatch_failpoint_degrades_to_flax_bitwise():
    dims = SHAPES["cartpole"]
    rssm_ref = _flax_rssm(dims, "reference")
    rssm_off = _flax_rssm(dims, "off")
    wm_params = _flax_params(rssm_off, dims, jax.random.PRNGKey(12))
    _, emb, act, isf, skey = _scan_data(dims, jax.random.PRNGKey(13))
    out_off = rssm_off.dynamic_scan(wm_params, emb, act, isf, skey)
    failpoints.configure("train.kernel_dispatch:fire")
    try:
        out_fp = rssm_ref.dynamic_scan(wm_params, emb, act, isf, skey)
    finally:
        failpoints.reset()
    for name, a_, b_ in zip(("h", "z", "prior_l", "post_l"), out_fp, out_off):
        assert bool(jnp.all(a_ == b_)), f"failpoint path must equal flax path ({name})"


# --------------------------------------------------------------------------- #
# dispatch units: select_impl, VMEM gate, extract_step_params
# --------------------------------------------------------------------------- #


def test_select_impl_knob_resolution():
    dims = SHAPES["cartpole"]
    spec = _spec(dims)
    assert K.select_impl("off", spec, 4) is None
    assert K.select_impl("reference", spec, 4) == "reference"
    assert K.select_impl("interpret", spec, 4) == "interpret"
    assert K.select_impl("auto", spec, 4, platform="cpu") == "reference"
    assert K.select_impl("auto", spec, 4, platform="tpu") == "pallas"
    with pytest.raises(ValueError):
        K.select_impl("turbo", spec, 4)


def test_select_impl_vmem_gate_degrades_not_crashes(monkeypatch):
    dims = SHAPES["cartpole"]
    spec = _spec(dims)
    monkeypatch.setenv("SHEEPRL_TPU_KERNEL_VMEM_BUDGET", "1024")  # nothing fits
    assert K.select_impl("pallas", spec, 4, platform="tpu") == "reference"
    assert K.select_impl("auto", spec, 4, platform="tpu") == "reference"
    monkeypatch.setenv("SHEEPRL_TPU_KERNEL_VMEM_BUDGET", str(1 << 40))
    assert K.select_impl("pallas", spec, 4, platform="tpu") == "pallas"


def test_step_vmem_bytes_scales_with_batch_and_dtype():
    dims = SHAPES["walker_walk"]
    f32 = _spec(dims, dtype="float32")
    bf16 = _spec(dims, dtype="bfloat16")
    assert K.step_vmem_bytes(f32, 64) > K.step_vmem_bytes(f32, 8)
    assert K.step_vmem_bytes(bf16, 8) < K.step_vmem_bytes(f32, 8)


def test_extract_step_params_rejects_unsupported_structures():
    dims = SHAPES["cartpole"]
    rssm = _flax_rssm(dims, "off")
    wm_params = _flax_params(rssm, dims, jax.random.PRNGKey(14))
    SD = dims["S"] * dims["D"]
    p = K.extract_step_params(wm_params, SD)
    assert set(p) == set(K.PARAM_KEYS)

    # a bias on the recurrent projection means layer_norm was off -> unsupported
    import copy

    broken = copy.deepcopy(jax.tree.map(lambda x: x, wm_params))
    dense = broken["recurrent_model"]["params"]["MLP_0"]["Dense_0"]
    dense["bias"] = jnp.zeros((dims["DU"],))
    with pytest.raises(K.KernelUnsupported):
        K.extract_step_params(broken, SD)

    # a second trunk layer is outside the fused single-layer contract
    broken2 = jax.tree.map(lambda x: x, wm_params)
    broken2["transition_model"]["params"]["MLP_0"] = dict(
        broken2["transition_model"]["params"]["MLP_0"]
    )
    broken2["transition_model"]["params"]["MLP_0"]["Dense_1"] = {
        "kernel": jnp.zeros((dims["HT"], dims["HT"]))
    }
    with pytest.raises(K.KernelUnsupported):
        K.extract_step_params(broken2, SD)


# --------------------------------------------------------------------------- #
# zero-host-transfer proof for the warmed fused scan
# --------------------------------------------------------------------------- #


def test_warm_fused_scan_makes_zero_host_transfers():
    dims = SHAPES["cartpole"]
    spec = _spec(dims)
    p = _raw_params(dims, jax.random.PRNGKey(15))
    init_raw, emb, act, isf, skey = _scan_data(dims, jax.random.PRNGKey(16))

    def scan(pp, ir, e_, a_, f_, k_):
        return K.fused_dynamic_scan(pp, spec, ir, e_, a_, f_, k_)

    gfn = jax_compile.guarded_jit(scan, name="test.fused_scan")
    args = (p, init_raw, emb, act, isf, skey)
    gfn.aot_compile(*jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), args))
    args = jax.device_put(args)
    jax.block_until_ready(gfn(*args))  # first dispatch through the AOT executable
    with jax.transfer_guard("disallow"):
        out = gfn(*args)
        jax.block_until_ready(out)  # fence only — not a transfer
