"""Parity tests for the Pallas fused LayerNorm-GRU cell.

On the CPU test mesh the kernel runs in interpret mode; on a real TPU the same
assertions hold compiled (bench/integration covers that). Forward AND backward
are compared against the pure-JAX reference implementation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops.pallas.gru import layer_norm_gru, layer_norm_gru_reference

INTERPRET = jax.default_backend() != "tpu"


def _rand_inputs(key, b, d, h):
    kx, kh, kw, kg, kb = jax.random.split(key, 5)
    x = jax.random.normal(kx, (b, d), jnp.float32)
    hs = jax.random.normal(kh, (b, h), jnp.float32)
    w = jax.random.normal(kw, (h + d, 3 * h), jnp.float32) * 0.1
    g = 1.0 + 0.1 * jax.random.normal(kg, (3 * h,), jnp.float32)
    bias = 0.1 * jax.random.normal(kb, (3 * h,), jnp.float32)
    return x, hs, w, g, bias


@pytest.mark.parametrize("b,d,h", [(8, 128, 128), (20, 128, 256), (300, 256, 128)])
def test_forward_matches_reference(b, d, h):
    x, hs, w, g, bias = _rand_inputs(jax.random.PRNGKey(0), b, d, h)
    out = layer_norm_gru(x, hs, w, g, bias, 1e-5, INTERPRET)
    ref = layer_norm_gru_reference(x, hs, w, g, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


# b=300 spans multiple row tiles (tb=256 -> grid=(2,)): exercises the
# @pl.when(i==0) zero-init + revisited-block accumulation of dw/dg/db
@pytest.mark.parametrize("b,d,h", [(8, 128, 128), (20, 128, 256), (300, 128, 128)])
def test_grads_match_reference(b, d, h):
    x, hs, w, g, bias = _rand_inputs(jax.random.PRNGKey(1), b, d, h)

    def loss_pallas(x, hs, w, g, bias):
        return jnp.sum(jnp.tanh(layer_norm_gru(x, hs, w, g, bias, 1e-5, INTERPRET)))

    def loss_ref(x, hs, w, g, bias):
        return jnp.sum(jnp.tanh(layer_norm_gru_reference(x, hs, w, g, bias)))

    grads_p = jax.grad(loss_pallas, argnums=(0, 1, 2, 3, 4))(x, hs, w, g, bias)
    grads_r = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(x, hs, w, g, bias)
    for gp, gr, name in zip(grads_p, grads_r, ["dx", "dh", "dw", "dg", "db"]):
        np.testing.assert_allclose(
            np.asarray(gp), np.asarray(gr), rtol=2e-4, atol=2e-4, err_msg=name
        )


def test_under_scan_and_jit():
    """The cell is stepped inside lax.scan in the RSSM; make sure that composes."""
    b, d, h = 16, 128, 128
    x, hs, w, g, bias = _rand_inputs(jax.random.PRNGKey(2), b, d, h)
    xs = jnp.stack([x, x * 0.5, -x, x * 2.0])

    @jax.jit
    def roll(hs, xs, w, g, bias):
        def step(carry, xt):
            hn = layer_norm_gru(xt, carry, w, g, bias, 1e-5, INTERPRET)
            return hn, hn
        return jax.lax.scan(step, hs, xs)

    def roll_ref(hs, xs, w, g, bias):
        def step(carry, xt):
            hn = layer_norm_gru_reference(xt, carry, w, g, bias)
            return hn, hn
        return jax.lax.scan(step, hs, xs)

    (hn, ys) = roll(hs, xs, w, g, bias)
    (hn_r, ys_r) = roll_ref(hs, xs, w, g, bias)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(ys_r), rtol=1e-4, atol=1e-4)

    # and gradients through the scan
    gp = jax.grad(lambda w: jnp.sum(roll(hs, xs, w, g, bias)[1]))(w)
    gr = jax.grad(lambda w: jnp.sum(roll_ref(hs, xs, w, g, bias)[1]))(w)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gr), rtol=2e-4, atol=2e-4)
