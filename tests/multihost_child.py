"""Child process for the 2-process multi-controller tests (test_multihost.py).

Run as: python tests/multihost_child.py <coordinator_port> <process_id> <num_processes> <tmpdir> [mode]

Covers, on the CPU backend over localhost (the same jax.distributed machinery a
TPU pod uses over DCN — reference counterpart: the reference's CPU-Gloo
multi-process tests, tests/test_algos/test_algos.py):
- mode "ok" (default): Runtime(multihost=True) boots against an
  externally-initialized jax.distributed (the launcher case) without raising;
  log-dir broadcast, DP gradient agreement, checkpoint write-once;
- mode "timeout": NO coordinator is listening — Runtime(multihost=True,
  coordinator_address=..., multihost_timeout_s=5) must raise the wrapped
  RuntimeError quickly instead of hanging for jax's 300 s default;
- mode "mismatch": processes boot with DIFFERENT local device counts (argv[6]);
  Runtime's homogeneity validation must raise on every process;
- mode "resume": checkpoint save (write-once) then load on both processes; the
  reloaded state must match bit-for-bit and the re-run log dir must version-bump
  on every process.

Prints one JSON line with the observed values; the parent asserts cross-process
equality.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_DEVCOUNT = sys.argv[6] if len(sys.argv) > 6 else "2"
flags = [f for f in os.environ.get("XLA_FLAGS", "").split() if "host_platform_device_count" not in f]
flags.append(f"--xla_force_host_platform_device_count={_DEVCOUNT}")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheeprl_tpu.core.runtime import enable_cpu_collectives  # noqa: E402

enable_cpu_collectives()  # gloo: CPU cross-process collectives (before backend init)


def _mode_timeout(port: int, pid: int, nproc: int) -> None:
    from sheeprl_tpu.core.runtime import Runtime

    try:
        Runtime(
            accelerator="cpu",
            devices="auto",
            multihost=True,
            coordinator_address=f"localhost:{port}",
            num_processes=nproc,
            process_id=pid,
            multihost_timeout_s=5,
        )
    except RuntimeError as e:
        print(json.dumps({"pid": pid, "raised": True, "msg": str(e)[:200]}))
        return
    print(json.dumps({"pid": pid, "raised": False}))


def _mode_mismatch(port: int, pid: int, nproc: int) -> None:
    from sheeprl_tpu.core.runtime import Runtime

    jax.distributed.initialize(f"localhost:{port}", num_processes=nproc, process_id=pid)
    try:
        Runtime(accelerator="cpu", devices=jax.local_device_count(), multihost=True)
    except RuntimeError as e:
        print(json.dumps({"pid": pid, "raised": True, "msg": str(e)[:300]}))
        return
    print(json.dumps({"pid": pid, "raised": False}))


def _mode_resume(port: int, pid: int, nproc: int, tmpdir: str) -> None:
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.core.runtime import Runtime
    from sheeprl_tpu.utils.checkpoint import load_state, save_state
    from sheeprl_tpu.utils.logger import get_log_dir

    jax.distributed.initialize(f"localhost:{port}", num_processes=nproc, process_id=pid)
    runtime = Runtime(accelerator="cpu", devices=jax.device_count(), multihost=True)
    os.chdir(tmpdir)

    # ---- "first run": train state + write-once checkpoint
    log_dir_1 = get_log_dir(runtime, "mh_resume", "run")
    params = runtime.replicate(jnp.arange(4, dtype=jnp.float32))
    ckpt = os.path.join(tmpdir, "ckpt_state.ckpt")
    if runtime.is_global_zero:
        save_state(ckpt, {"params": params, "iter_num": 123})
    runtime.barrier()

    # ---- "resume": every process loads the same state; log dir version-bumps
    state = load_state(ckpt)
    log_dir_2 = get_log_dir(runtime, "mh_resume", "run")
    loaded = np.asarray(state["params"])
    print(
        json.dumps(
            {
                "pid": pid,
                "iter_num": int(state["iter_num"]),
                "loaded": loaded.reshape(-1).tolist(),
                "expected": np.arange(4, dtype=np.float32).tolist(),
                "log_dir_1": log_dir_1,
                "log_dir_2": log_dir_2,
            }
        )
    )


def main() -> None:
    port, pid, nproc, tmpdir = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    mode = sys.argv[5] if len(sys.argv) > 5 else "ok"
    if mode == "timeout":
        return _mode_timeout(port, pid, nproc)
    if mode == "mismatch":
        return _mode_mismatch(port, pid, nproc)
    if mode == "resume":
        return _mode_resume(port, pid, nproc, tmpdir)
    jax.distributed.initialize(f"localhost:{port}", num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc, jax.process_count()

    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sheeprl_tpu.core.runtime import Runtime
    from sheeprl_tpu.utils.logger import get_log_dir

    # multihost=True with distributed already initialized (launcher case) must not raise
    runtime = Runtime(accelerator="cpu", devices=jax.device_count(), multihost=True)
    assert runtime.world_size == nproc * 2, runtime.world_size

    os.chdir(tmpdir)  # log dirs are relative to cwd
    log_dir = get_log_dir(runtime, "mh_algo", "mh_run")

    # ---- DP gradient agreement over the global mesh
    data_sharding = NamedSharding(runtime.mesh, P("data"))
    w = runtime.replicate(jnp.full((2,), 0.5, jnp.float32))
    # each process owns a DIFFERENT local slice of the global [4, 2] batch
    local = np.arange(2 * 2, dtype=np.float32).reshape(2, 2) + 100.0 * pid
    batch = jax.make_array_from_process_local_data(data_sharding, local, (4, 2))

    @jax.jit
    def grad_fn(w, x):
        return jax.grad(lambda w: jnp.mean(jnp.sum(x * w[None, :], axis=-1) ** 2))(w)

    g = grad_fn(w, batch)
    # replicated output: each process reads its own addressable replica; the parent
    # asserts the two processes report the SAME value, i.e. XLA inserted the
    # cross-process reduction (the DDP allreduce equivalent)
    g_local = np.asarray(jax.device_get(g.addressable_data(0)))

    # ---- checkpoint write-once
    ckpt = os.path.join(tmpdir, f"ckpt_shared.npz")
    if runtime.is_global_zero:
        np.savez(ckpt, w=np.asarray(jax.device_get(w)))
    runtime.barrier()
    assert os.path.exists(ckpt)

    print(
        json.dumps(
            {
                "pid": pid,
                "log_dir": log_dir,
                "grad": np.asarray(g_local).reshape(-1).round(6).tolist(),
                "ckpt_exists": os.path.exists(ckpt),
            }
        )
    )


if __name__ == "__main__":
    main()
