import numpy as np
import pytest

from sheeprl_tpu.data.buffers import ReplayBuffer


def _data(t, n, key="observations", extra=()):
    d = {key: np.arange(t * n).reshape(t, n, 1).astype(np.float32)}
    for k in extra:
        d[k] = np.zeros((t, n, 1), dtype=np.float32)
    return d


def test_init_validation():
    with pytest.raises(ValueError):
        ReplayBuffer(0)
    with pytest.raises(ValueError):
        ReplayBuffer(4, 0)


def test_add_and_wraparound():
    rb = ReplayBuffer(buffer_size=4, n_envs=2)
    rb.add(_data(3, 2))
    assert not rb.full
    rb.add(_data(3, 2))
    assert rb.full
    assert rb["observations"].shape == (4, 2, 1)
    # pos should have wrapped to 2
    assert rb._pos == 2


def test_add_longer_than_buffer():
    rb = ReplayBuffer(buffer_size=4, n_envs=1)
    data = _data(10, 1)
    rb.add(data)
    assert rb.full
    # keeps the most recent rows
    assert float(rb["observations"].max()) == 9.0


def test_add_validate_args():
    rb = ReplayBuffer(4, 1)
    with pytest.raises(ValueError):
        rb.add([1, 2, 3], validate_args=True)
    with pytest.raises(ValueError):
        rb.add({"a": [1]}, validate_args=True)
    with pytest.raises(RuntimeError):
        rb.add({"a": np.zeros((4,))}, validate_args=True)
    with pytest.raises(RuntimeError):
        rb.add({"a": np.zeros((4, 1, 1)), "b": np.zeros((3, 1, 1))}, validate_args=True)


def test_sample_shape():
    rb = ReplayBuffer(8, 2)
    rb.add(_data(8, 2))
    s = rb.sample(5, n_samples=3)
    assert s["observations"].shape == (3, 5, 1)


def test_sample_errors():
    rb = ReplayBuffer(8, 1)
    with pytest.raises(ValueError):
        rb.sample(0)
    with pytest.raises(ValueError):
        rb.sample(1)  # empty
    rb.add(_data(1, 1))
    with pytest.raises(RuntimeError):
        rb.sample(1, sample_next_obs=True)  # needs at least 2


def test_sample_next_obs():
    rb = ReplayBuffer(8, 1)
    rb.add(_data(6, 1))
    s = rb.sample(16, sample_next_obs=True)
    assert "next_observations" in s
    np.testing.assert_allclose(s["next_observations"], s["observations"] + 1)


def test_sample_next_obs_wraparound_validity():
    rb = ReplayBuffer(4, 1)
    rb.add(_data(6, 1))  # pos=2, full
    s = rb.sample(64, sample_next_obs=True)
    # the transition crossing the write head (pos-1 -> pos) must never be sampled
    assert not np.any(s["observations"] == 1.0) or np.all(
        s["next_observations"][s["observations"] == 1.0] == 2.0
    )


def test_memmap_buffer(tmp_path):
    rb = ReplayBuffer(8, 2, memmap=True, memmap_dir=tmp_path / "rb")
    rb.add(_data(4, 2))
    assert rb.is_memmap
    s = rb.sample(3)
    assert s["observations"].shape == (1, 3, 1)
    assert (tmp_path / "rb" / "observations.memmap").exists()


def test_memmap_requires_dir():
    with pytest.raises(ValueError):
        ReplayBuffer(8, 1, memmap=True, memmap_dir=None)


def test_memmap_invalid_mode(tmp_path):
    with pytest.raises(ValueError):
        ReplayBuffer(8, 1, memmap=True, memmap_dir=tmp_path, memmap_mode="r")


def test_getitem_setitem():
    rb = ReplayBuffer(4, 2)
    with pytest.raises(RuntimeError):
        rb["observations"]
    rb.add(_data(2, 2))
    with pytest.raises(TypeError):
        rb[0]
    rb["new_key"] = np.ones((4, 2, 3), dtype=np.float32)
    assert rb["new_key"].shape == (4, 2, 3)
    with pytest.raises(RuntimeError):
        rb["bad"] = np.ones((2, 2))
    with pytest.raises(ValueError):
        rb["bad"] = "not an array"


def test_sample_arrays_device():
    import jax

    rb = ReplayBuffer(8, 1)
    rb.add(_data(8, 1))
    out = rb.sample_arrays(4, device=jax.devices()[0])
    assert isinstance(out["observations"], jax.Array)


def test_state_dict_roundtrip():
    rb = ReplayBuffer(8, 2)
    rb.add(_data(5, 2))
    state = rb.state_dict()
    rb2 = ReplayBuffer(8, 2)
    rb2.load_state_dict(state)
    np.testing.assert_array_equal(np.asarray(rb2["observations"]), np.asarray(rb["observations"]))
    assert rb2._pos == rb._pos and rb2.full == rb.full
