"""DeviceSequentialReplayBuffer: HBM-resident storage/sampling parity checks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data.device_buffer import DeviceSequentialReplayBuffer


def _step(t, n_envs=2, extra=0.0):
    """A recognizable [1, n_envs, ...] transition: values encode (t, env)."""
    base = np.arange(n_envs, dtype=np.float32)[None, :]
    return {
        "obs": np.full((1, n_envs, 3), t, dtype=np.float32) + base[..., None] * 100 + extra,
        "rewards": np.full((1, n_envs, 1), t, dtype=np.float32),
        "pix": np.full((1, n_envs, 2, 4, 4), t % 256, dtype=np.uint8),
    }


def test_add_and_sample_shapes_on_device():
    rb = DeviceSequentialReplayBuffer(16, n_envs=2)
    rb.seed(0)
    for t in range(8):
        rb.add(_step(t))
    out = rb.sample(batch_size=3, sequence_length=4, n_samples=2)
    assert out["obs"].shape == (2, 4, 3, 3)
    assert out["pix"].shape == (2, 4, 3, 2, 4, 4)
    assert isinstance(out["obs"], jax.Array)
    assert out["pix"].dtype == jnp.uint8


def test_sequences_are_consecutive():
    rb = DeviceSequentialReplayBuffer(32, n_envs=2)
    rb.seed(1)
    for t in range(20):
        rb.add(_step(t))
    out = rb.sample(batch_size=8, sequence_length=5, n_samples=3)
    rew = np.asarray(out["rewards"])  # [G, T, B, 1]
    diffs = np.diff(rew[..., 0], axis=1)
    np.testing.assert_array_equal(diffs, np.ones_like(diffs))


def test_wraparound_never_crosses_write_head():
    rb = DeviceSequentialReplayBuffer(8, n_envs=1)
    rb.seed(2)
    for t in range(20):  # wraps 2.5x
        rb.add(_step(t, n_envs=1))
    out = rb.sample(batch_size=64, sequence_length=3, n_samples=1)
    rew = np.asarray(out["rewards"])[0, :, :, 0]  # [T, B]
    # all sampled values must come from the last 8 steps, consecutive
    assert rew.min() >= 12
    np.testing.assert_array_equal(np.diff(rew, axis=0), np.ones_like(np.diff(rew, axis=0)))


def test_partial_env_add_advances_only_those_envs():
    rb = DeviceSequentialReplayBuffer(16, n_envs=3)
    rb.seed(3)
    for t in range(4):
        rb.add(_step(t, n_envs=3))
    rb.add({k: v[:, :2] for k, v in _step(99, n_envs=3).items()}, indices=[0, 2])
    assert rb._pos.tolist() == [5, 4, 5]
    # env 1's head is untouched; envs 0/2 got the extra row
    buf = {k: np.asarray(jax.device_get(v)) for k, v in rb.buffer.items()}
    assert buf["rewards"][4, 0, 0] == 99
    assert buf["rewards"][4, 2, 0] == 99
    assert buf["rewards"][4, 1, 0] == 0  # untouched slot


def test_too_short_raises():
    rb = DeviceSequentialReplayBuffer(16, n_envs=1)
    rb.add(_step(0, n_envs=1))
    with pytest.raises(ValueError, match="not enough history"):
        rb.sample(batch_size=1, sequence_length=4)


def test_checkpoint_roundtrip():
    rb = DeviceSequentialReplayBuffer(8, n_envs=2)
    rb.seed(4)
    for t in range(11):
        rb.add(_step(t))
    state = rb.state_dict()
    rb2 = DeviceSequentialReplayBuffer(8, n_envs=2)
    rb2.load_state_dict(state)
    rb2.seed(4)
    assert rb2._pos.tolist() == rb._pos.tolist()
    assert rb2.full == rb.full
    a = np.asarray(rb.sample(batch_size=4, sequence_length=3)["obs"])
    b = np.asarray(rb2.sample(batch_size=4, sequence_length=3)["obs"])
    np.testing.assert_array_equal(a, b)


def test_dtype_narrowing_and_uint8_storage():
    rb = DeviceSequentialReplayBuffer(4, n_envs=1)
    rb.add({"a": np.zeros((1, 1, 2), dtype=np.float64), "b": np.zeros((1, 1, 2), dtype=np.int64)})
    assert rb.buffer["a"].dtype == jnp.float32
    assert rb.buffer["b"].dtype == jnp.int32


def test_later_add_with_different_dtype_is_coerced_not_bitcast():
    """A leaf arriving with a dtype that differs from the allocation-time storage
    dtype must be VALUE-cast before packing: the packed byte stream is decoded
    with the storage dtype, so a same-itemsize mismatch (int32 vs float32) would
    otherwise silently reinterpret bits, and a different itemsize would misalign
    every later leaf in the stream."""
    rb = DeviceSequentialReplayBuffer(8, n_envs=1)
    rb.add({"r": np.full((1, 1, 1), 1.0, dtype=np.float32), "z": np.zeros((1, 1, 2), np.float32)})
    # same itemsize, different kind: int32 values 7 must land as float32 7.0
    rb.add({"r": np.full((1, 1, 1), 7, dtype=np.int32), "z": np.ones((1, 1, 2), np.float32)})
    # different itemsize: float16 3.0 must not shift the byte offsets of 'z'
    rb.add({"r": np.full((1, 1, 1), 3.0, dtype=np.float16), "z": np.full((1, 1, 2), 5.0, np.float32)})
    buf = {k: np.asarray(jax.device_get(v)) for k, v in rb.buffer.items()}
    np.testing.assert_array_equal(buf["r"][:3, 0, 0], [1.0, 7.0, 3.0])
    np.testing.assert_array_equal(buf["z"][2, 0, :2], [5.0, 5.0])


def test_dv3_cli_with_device_buffer(tmp_path, monkeypatch):
    """End-to-end DV3 smoke over the HBM-resident buffer path."""
    monkeypatch.chdir(tmp_path)
    from sheeprl_tpu.cli import run

    run(
        overrides=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "dry_run=True",
            "metric.log_level=0",
            "checkpoint.save_last=False",
            "fabric.devices=1",
            "buffer.device=True",
            "algo.learning_starts=0",
            "algo.per_rank_sequence_length=1",
            "algo.per_rank_batch_size=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=2",
            "algo.world_model.recurrent_model.recurrent_state_size=8",
            "algo.world_model.representation_model.hidden_size=8",
            "algo.world_model.transition_model.hidden_size=8",
            "algo.world_model.discrete_size=2",
            "algo.world_model.stochastic_size=2",
            "algo.horizon=3",
        ]
    )


def test_dv1_cli_with_device_buffer(tmp_path, monkeypatch):
    """DV1's sequential path supports the HBM-resident buffer too (its
    pixel-target recipe now defaults to it — host-buffer runs leak transport
    staging memory on tunneled accelerators)."""
    monkeypatch.chdir(tmp_path)
    from sheeprl_tpu.cli import run

    run(
        overrides=[
            "exp=dreamer_v1",
            "env=dummy",
            "env.id=discrete_dummy",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "dry_run=True",
            "metric.log_level=0",
            "checkpoint.save_last=False",
            "fabric.devices=1",
            "buffer.device=True",
            "algo.learning_starts=0",
            "algo.per_rank_sequence_length=1",
            "algo.per_rank_batch_size=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=2",
            "algo.world_model.recurrent_model.recurrent_state_size=8",
            "algo.world_model.representation_model.hidden_size=8",
            "algo.world_model.transition_model.hidden_size=8",
            "algo.world_model.stochastic_size=2",
            "algo.horizon=3",
        ]
    )


def test_dv2_cli_with_device_buffer(tmp_path, monkeypatch):
    """DV2's sequential path supports the HBM-resident buffer too."""
    monkeypatch.chdir(tmp_path)
    from sheeprl_tpu.cli import run

    run(
        overrides=[
            "exp=dreamer_v2",
            "env=dummy",
            "env.id=discrete_dummy",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "dry_run=True",
            "metric.log_level=0",
            "checkpoint.save_last=False",
            "fabric.devices=1",
            "buffer.device=True",
            "algo.learning_starts=0",
            "algo.per_rank_sequence_length=1",
            "algo.per_rank_batch_size=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=2",
            "algo.world_model.recurrent_model.recurrent_state_size=8",
            "algo.world_model.representation_model.hidden_size=8",
            "algo.world_model.transition_model.hidden_size=8",
            "algo.world_model.discrete_size=2",
            "algo.world_model.stochastic_size=2",
            "algo.horizon=3",
        ]
    )


def test_episode_buffer_rejects_device(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    from sheeprl_tpu.cli import run

    with pytest.raises(ValueError, match="sequential replay only"):
        run(
            overrides=[
                "exp=dreamer_v2",
                "env=dummy",
                "env.id=discrete_dummy",
                "env.num_envs=2",
                "env.sync_env=True",
                "env.capture_video=False",
                "dry_run=True",
                "metric.log_level=0",
                "checkpoint.save_last=False",
                "fabric.devices=1",
                "buffer.device=True",
                "buffer.type=episode",
            ]
        )
