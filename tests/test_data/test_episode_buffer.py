import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EpisodeBuffer


def _episode(length, n_envs=1, terminated_at_end=True):
    d = {
        "observations": np.arange(length * n_envs).reshape(length, n_envs, 1).astype(np.float32),
        "terminated": np.zeros((length, n_envs, 1), dtype=np.float32),
        "truncated": np.zeros((length, n_envs, 1), dtype=np.float32),
    }
    if terminated_at_end:
        d["terminated"][-1] = 1
    return d


def test_init_validation():
    with pytest.raises(ValueError):
        EpisodeBuffer(0, 1)
    with pytest.raises(ValueError):
        EpisodeBuffer(8, 0)
    with pytest.raises(ValueError):
        EpisodeBuffer(4, 8)


def test_add_complete_episode():
    eb = EpisodeBuffer(32, minimum_episode_length=2)
    eb.add(_episode(5))
    assert len(eb) == 5
    assert len(eb.buffer) == 1


def test_open_episode_accumulates():
    eb = EpisodeBuffer(32, 2)
    eb.add(_episode(3, terminated_at_end=False))
    assert len(eb) == 0  # still open
    eb.add(_episode(3))
    assert len(eb) == 6


def test_too_short_episode_raises():
    eb = EpisodeBuffer(32, 4)
    with pytest.raises(RuntimeError):
        eb.add(_episode(2))


def test_eviction_of_oldest():
    eb = EpisodeBuffer(10, 2)
    eb.add(_episode(4))
    eb.add(_episode(4))
    eb.add(_episode(4))  # 12 > 10: first must be evicted
    assert len(eb) <= 10
    assert len(eb.buffer) == 2


def test_sample_shapes():
    eb = EpisodeBuffer(64, 2)
    eb.add(_episode(10))
    eb.add(_episode(8))
    s = eb.sample(3, n_samples=2, sequence_length=4)
    assert s["observations"].shape == (2, 4, 3, 1)


def test_sample_no_valid_episode():
    eb = EpisodeBuffer(64, 2)
    eb.add(_episode(3))
    with pytest.raises(RuntimeError):
        eb.sample(1, sequence_length=10)


def test_sample_next_obs():
    eb = EpisodeBuffer(64, 2, obs_keys=("observations",))
    eb.add(_episode(10))
    s = eb.sample(4, sequence_length=3, sample_next_obs=True)
    np.testing.assert_allclose(s["next_observations"][..., 0], s["observations"][..., 0] + 1)


def test_prioritize_ends_samples_tail():
    eb = EpisodeBuffer(64, 2, prioritize_ends=True)
    eb.add(_episode(8))
    s = eb.sample(64, sequence_length=4)
    # with prioritize_ends the last window (starting at ep_len - L) must appear
    starts = s["observations"][0, 0, :, 0]
    assert (starts == 4).any()


def test_memmap_episode(tmp_path):
    eb = EpisodeBuffer(32, 2, memmap=True, memmap_dir=tmp_path / "ep")
    eb.add(_episode(6))
    assert eb.is_memmap
    s = eb.sample(2, sequence_length=3)
    assert s["observations"].shape == (1, 3, 2, 1)


def test_multi_env_split():
    eb = EpisodeBuffer(64, 2, n_envs=2)
    data = _episode(6, n_envs=2)
    eb.add(data)
    assert len(eb.buffer) == 2
