"""DevicePrefetcher: overlap semantics, speculation reuse/discard, error paths."""

import threading

import jax
import numpy as np
import pytest

from sheeprl_tpu.data.prefetch import DevicePrefetcher


class CountingSampler:
    """sample_fn double that records calls and returns identifiable batches."""

    def __init__(self):
        self.calls = []
        self.lock = threading.Lock()

    def __call__(self, **kwargs):
        with self.lock:
            self.calls.append(dict(kwargs))
            n = len(self.calls)
        size = int(kwargs.get("batch_size", 1))
        return {"x": np.full((size, 2), n, dtype=np.float32)}


def test_first_get_is_synchronous_and_speculates():
    s = CountingSampler()
    with DevicePrefetcher(s) as pf:
        out = pf.get(batch_size=3)
        assert out["x"].shape == (3, 2)
        # first call: one sync sample; a speculative one is (or will be) in flight
        assert {"batch_size": 3} in s.calls


def test_speculation_consumed_on_matching_kwargs():
    s = CountingSampler()
    with DevicePrefetcher(s) as pf:
        a = pf.get(batch_size=2)
        b = pf.get(batch_size=2)  # must consume the speculative batch, not resample inline
        # batches are distinct samples (different fill values)
        assert not np.array_equal(a["x"], b["x"])
        # after two gets: 1 sync + at least the consumed speculation
        assert len([c for c in s.calls if c == {"batch_size": 2}]) >= 2


def test_kwargs_change_discards_speculation():
    s = CountingSampler()
    with DevicePrefetcher(s) as pf:
        a = pf.get(batch_size=2)
        b = pf.get(batch_size=5)  # mismatch: stale speculation must not be returned
        assert a["x"].shape == (2, 2)
        assert b["x"].shape == (5, 2)
        c = pf.get(batch_size=5)  # steady state again
        assert c["x"].shape == (5, 2)


def test_many_iterations_matches_sync_shapes():
    s = CountingSampler()
    with DevicePrefetcher(s) as pf:
        seen = set()
        for _ in range(20):
            out = pf.get(batch_size=4)
            assert out["x"].shape == (4, 2)
            seen.add(float(out["x"][0, 0]))
        # each get must return a fresh sample, never a repeated speculation
        assert len(seen) == 20


def test_device_placement():
    s = CountingSampler()
    dev = jax.devices()[0]
    with DevicePrefetcher(s, device=dev) as pf:
        out = pf.get(batch_size=2)
        assert isinstance(out["x"], jax.Array)
        assert out["x"].devices() == {dev}
        out2 = pf.get(batch_size=2)
        assert isinstance(out2["x"], jax.Array)


def test_sharded_placement():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))
    sharding = NamedSharding(mesh, P(None, "data"))

    def sample(**kwargs):
        return {"x": np.zeros((3, 8, 5), dtype=np.float32)}

    with DevicePrefetcher(sample, device=sharding) as pf:
        out = pf.get()
        assert out["x"].sharding == sharding
        out2 = pf.get()
        assert out2["x"].sharding == sharding


def test_error_propagates_sync_and_speculative():
    calls = {"n": 0}

    def flaky(**kwargs):
        calls["n"] += 1
        raise ValueError(f"boom {calls['n']}")

    with DevicePrefetcher(flaky) as pf:
        with pytest.raises(ValueError, match="boom"):
            pf.get(batch_size=1)
        # the speculative job also failed; its error must surface on the next get
        with pytest.raises(ValueError, match="boom"):
            pf.get(batch_size=1)


def test_dtype_narrowing():
    def sample(**kwargs):
        return {"x": np.zeros((2, 2), dtype=np.float64)}

    with DevicePrefetcher(sample, device=jax.devices()[0]) as pf:
        out = pf.get()
        assert out["x"].dtype == np.float32  # f64 narrowed to TPU-native width


class ChunkSampler:
    """Returns [g, 2] batches whose values identify the sample call."""

    def __init__(self):
        self.calls = []
        self.lock = threading.Lock()

    def __call__(self, *, g):
        with self.lock:
            self.calls.append(g)
            n = len(self.calls)
        return {"x": (np.arange(g, dtype=np.float32)[:, None] + 100 * n) * np.ones((g, 2), np.float32)}


def test_chunked_one_sample_serves_chunk_gets():
    s = ChunkSampler()
    with DevicePrefetcher(s, chunk=4, chunk_key="g") as pf:
        outs = [pf.get(g=2) for _ in range(9)]
        for o in outs:
            assert np.asarray(o["x"]).shape == (2, 2)
        # call 1: sync single (g=2); then scaled superbatches (g=8) each serving 4 gets:
        # 9 gets = 1 sync + 2 consumed superbatches (and a third speculating)
        assert s.calls[0] == 2
        assert all(c == 8 for c in s.calls[1:])
        assert len([c for c in s.calls if c == 8]) <= 4
        # pieces of one superbatch are distinct slices (offset by the arange)
        vals = [float(np.asarray(o["x"])[0, 0]) for o in outs]
        assert len(set(vals)) == len(vals)


def test_chunked_kwargs_change_resets():
    s = ChunkSampler()
    with DevicePrefetcher(s, chunk=3, chunk_key="g") as pf:
        a = pf.get(g=2)
        b = pf.get(g=5)  # g changed: stale pieces/speculation must be discarded
        assert np.asarray(a["x"]).shape == (2, 2)
        assert np.asarray(b["x"]).shape == (5, 2)
        c = pf.get(g=5)
        assert np.asarray(c["x"]).shape == (5, 2)


def test_chunked_error_propagates():
    def flaky(**kwargs):
        raise ValueError("boom")

    with DevicePrefetcher(flaky, chunk=2, chunk_key="g") as pf:
        with pytest.raises(ValueError, match="boom"):
            pf.get(g=1)
        with pytest.raises(ValueError, match="boom"):
            pf.get(g=1)


def test_chunked_device_slices():
    s = ChunkSampler()
    dev = jax.devices()[0]
    with DevicePrefetcher(s, device=dev, chunk=2, chunk_key="g") as pf:
        outs = [pf.get(g=3) for _ in range(4)]
        for o in outs:
            assert isinstance(o["x"], jax.Array)
            assert o["x"].shape == (3, 2)


def test_close_idempotent():
    s = CountingSampler()
    pf = DevicePrefetcher(s)
    pf.get(batch_size=1)
    pf.close()
    pf.close()
    with pytest.raises(RuntimeError):
        pf.get(batch_size=1)
