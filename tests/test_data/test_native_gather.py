"""Parity tests: C++ seq_gather vs the numpy gather path.

The native extension builds on first use (g++ baked into the image); if the
build is unavailable the module returns None and the tests skip — the buffers
then always use the (equally tested) numpy path.
"""

import numpy as np
import pytest

from sheeprl_tpu.data.buffers import SequentialReplayBuffer
from sheeprl_tpu.native import native_available, seq_gather

pytestmark = pytest.mark.skipif(not native_available(), reason="native extension unavailable")


def _reference(src, starts, envs, n_samples, b, L):
    out = np.empty((n_samples, L, b) + src.shape[2:], dtype=src.dtype)
    for p in range(n_samples * b):
        n, bb = divmod(p, b)
        for t in range(L):
            out[n, t, bb] = src[(starts[p] + t) % src.shape[0], envs[p]]
    return out


@pytest.mark.parametrize("dtype", [np.float32, np.uint8, np.float64])
@pytest.mark.parametrize("feat", [(4,), (3, 8, 8), ()])
def test_seq_gather_matches_reference(dtype, feat):
    rng = np.random.default_rng(0)
    cap, n_envs, n_samples, b, L = 37, 3, 4, 5, 7
    src = (rng.random((cap, n_envs, *feat)) * 100).astype(dtype)
    starts = rng.integers(0, cap, size=(n_samples * b,), dtype=np.int64)  # incl. wraparound
    envs = rng.integers(0, n_envs, size=(n_samples * b,), dtype=np.int64)
    out = seq_gather(src, starts, envs, n_samples, b, L)
    np.testing.assert_array_equal(out, _reference(src, starts, envs, n_samples, b, L))


def test_sequential_buffer_native_matches_numpy_path(monkeypatch):
    """Same seed => same sampled indices => identical outputs on both paths."""
    def fill(rb, steps, n_envs):
        for i in range(steps):
            rb.add(
                {
                    "obs": np.full((1, n_envs, 4), i, dtype=np.float32),
                    "rewards": np.full((1, n_envs, 1), i, dtype=np.float32),
                },
                validate_args=True,
            )

    out = {}
    for use_native in (True, False):
        rb = SequentialReplayBuffer(16, n_envs=2, obs_keys=("obs",))
        fill(rb, 24, 2)  # wraps around
        rb.seed(1234)
        if not use_native:
            monkeypatch.setattr("sheeprl_tpu.data.buffers._native_seq_gather", lambda: None)
        out[use_native] = rb.sample(batch_size=6, n_samples=3, sequence_length=5, sample_next_obs=True)
        monkeypatch.undo()
    for k in out[True]:
        np.testing.assert_array_equal(out[True][k], out[False][k], err_msg=k)
        assert out[True][k].shape == out[False][k].shape
