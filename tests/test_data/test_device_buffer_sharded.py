"""ShardedDeviceSequentialReplayBuffer: mesh-sharded HBM replay on the CPU mesh.

The data-parallel device-buffer contract (reference per-rank host buffers,
sheeprl/data/buffers.py:529-744): env columns shard over the mesh's data axis,
each device samples only from its own envs, and the gathered batch lands
already sharded for the train step — no bulk host transfer, no cross-device
gather.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sheeprl_tpu.data.device_buffer import ShardedDeviceSequentialReplayBuffer

# Everything here is single-PROCESS data parallelism: a host-local 2-device mesh
# (conftest forces 8 virtual CPU devices). A world where this process cannot
# address 2 devices is a genuinely multi-process topology — the cross-host
# variants of these paths live in tests/test_utils/test_multihost.py — so skip
# with a reason instead of letting the mesh fixture fail.
pytestmark = pytest.mark.skipif(
    len(jax.local_devices()) < 2,
    reason="needs a host-local 2-device mesh (multi-process topologies are covered by test_multihost.py)",
)


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.local_devices()[:2]), ("data",))


def _step(t, n_envs, extra=0.0):
    """Values encode (t, env): obs = t + 100*env (+extra)."""
    base = np.arange(n_envs, dtype=np.float32)[None, :]
    return {
        "obs": np.full((1, n_envs, 3), t, dtype=np.float32) + base[..., None] * 100 + extra,
        "rewards": np.full((1, n_envs, 1), t, dtype=np.float32),
        "terminated": np.zeros((1, n_envs, 1), dtype=np.float32),
        "truncated": np.zeros((1, n_envs, 1), dtype=np.float32),
    }


def test_requires_divisible_envs(mesh):
    with pytest.raises(ValueError, match="divisible"):
        ShardedDeviceSequentialReplayBuffer(16, n_envs=3, mesh=mesh)


def test_storage_is_sharded_on_env_axis(mesh):
    rb = ShardedDeviceSequentialReplayBuffer(16, n_envs=4, mesh=mesh)
    rb.add(_step(0, 4))
    leaf = rb.buffer["obs"]
    assert leaf.shape == (16, 4, 3)
    shard_shapes = {s.data.shape for s in leaf.addressable_shards}
    assert shard_shapes == {(16, 2, 3)}  # 2 envs per device


def test_sample_layout_and_sharding(mesh):
    rb = ShardedDeviceSequentialReplayBuffer(32, n_envs=4, mesh=mesh)
    rb.seed(0)
    for t in range(10):
        rb.add(_step(t, 4))
    out = rb.sample(batch_size=6, sequence_length=4, n_samples=2)
    assert out["obs"].shape == (2, 4, 6, 3)
    # batch axis sharded over 'data': each device holds [G, T, 3] of it
    shard_shapes = {s.data.shape for s in out["obs"].addressable_shards}
    assert shard_shapes == {(2, 4, 3, 3)}
    expected = NamedSharding(mesh, P(None, None, "data"))
    assert out["obs"].sharding.is_equivalent_to(expected, out["obs"].ndim)


def test_each_device_samples_its_own_envs(mesh):
    rb = ShardedDeviceSequentialReplayBuffer(32, n_envs=4, mesh=mesh)
    rb.seed(1)
    for t in range(12):
        rb.add(_step(t, 4))
    out = rb.sample(batch_size=8, sequence_length=3, n_samples=2)
    obs = out["obs"]  # [G, T, B, 3]; env id = (value // 100)
    for shard in obs.addressable_shards:
        dev_index = shard.index[2].start // 4  # batch-axis chunk -> device 0 or 1
        envs = np.unique(np.asarray(shard.data)[..., 0] // 100).astype(int)
        local = set(range(dev_index * 2, dev_index * 2 + 2))
        assert set(envs.tolist()) <= local, f"device {dev_index} sampled foreign envs {envs}"


def test_sequences_are_consecutive(mesh):
    rb = ShardedDeviceSequentialReplayBuffer(64, n_envs=2, mesh=mesh)
    rb.seed(2)
    for t in range(40):
        rb.add(_step(t, 2))
    out = rb.sample(batch_size=8, sequence_length=5, n_samples=3)
    rew = np.asarray(out["rewards"])  # [G, T, B, 1]
    diffs = np.diff(rew[..., 0], axis=1)
    np.testing.assert_array_equal(diffs, np.ones_like(diffs))


def test_wraparound_never_crosses_write_head(mesh):
    rb = ShardedDeviceSequentialReplayBuffer(8, n_envs=2, mesh=mesh)
    rb.seed(3)
    for t in range(20):  # wraps 2.5x
        rb.add(_step(t, 2))
    out = rb.sample(batch_size=32, sequence_length=3, n_samples=1)
    rew = np.asarray(out["rewards"])[0, :, :, 0]  # [T, B]
    assert rew.min() >= 12
    np.testing.assert_array_equal(np.diff(rew, axis=0), np.ones_like(np.diff(rew, axis=0)))


def test_partial_env_add_advances_only_those_envs(mesh):
    rb = ShardedDeviceSequentialReplayBuffer(16, n_envs=4, mesh=mesh)
    rb.seed(4)
    for t in range(4):
        rb.add(_step(t, 4))
    rb.add({k: v[:, :2] for k, v in _step(99, 4).items()}, indices=[0, 3])
    assert rb._pos.tolist() == [5, 4, 4, 5]
    buf = {k: np.asarray(jax.device_get(v)) for k, v in rb.buffer.items()}
    assert buf["rewards"][4, 0, 0] == 99
    assert buf["rewards"][4, 3, 0] == 99
    assert buf["rewards"][4, 1, 0] == 0  # untouched slots
    assert buf["rewards"][4, 2, 0] == 0


def test_patch_last(mesh):
    rb = ShardedDeviceSequentialReplayBuffer(16, n_envs=2, mesh=mesh)
    for t in range(3):
        rb.add(_step(t, 2))
    rb.patch_last([1], {"terminated": 1.0, "rewards": -5.0})
    buf = {k: np.asarray(jax.device_get(v)) for k, v in rb.buffer.items()}
    assert buf["terminated"][2, 1, 0] == 1.0
    assert buf["rewards"][2, 1, 0] == -5.0
    assert buf["terminated"][2, 0, 0] == 0.0  # other env untouched
    assert buf["rewards"][2, 0, 0] == 2.0


def test_checkpoint_truncated_patch_roundtrip(mesh):
    rb = ShardedDeviceSequentialReplayBuffer(16, n_envs=2, mesh=mesh)
    for t in range(5):
        rb.add(_step(t, 2))
    undo = rb._patch_truncated()
    buf = np.asarray(jax.device_get(rb.buffer["truncated"]))
    assert buf[4, 0, 0] == 1.0 and buf[4, 1, 0] == 1.0
    rb._unpatch_truncated(undo)
    buf = np.asarray(jax.device_get(rb.buffer["truncated"]))
    assert buf[4, 0, 0] == 0.0 and buf[4, 1, 0] == 0.0


def test_checkpoint_roundtrip(mesh):
    rb = ShardedDeviceSequentialReplayBuffer(8, n_envs=2, mesh=mesh)
    rb.seed(5)
    for t in range(11):
        rb.add(_step(t, 2))
    state = rb.state_dict()
    rb2 = ShardedDeviceSequentialReplayBuffer(8, n_envs=2, mesh=mesh)
    rb2.load_state_dict(state)
    rb2.seed(5)
    assert rb2._pos.tolist() == rb._pos.tolist()
    assert rb2.full == rb.full
    a = np.asarray(rb.sample(batch_size=4, sequence_length=3)["obs"])
    b = np.asarray(rb2.sample(batch_size=4, sequence_length=3)["obs"])
    np.testing.assert_array_equal(a, b)


def test_batch_size_divisibility(mesh):
    rb = ShardedDeviceSequentialReplayBuffer(16, n_envs=2, mesh=mesh)
    for t in range(8):
        rb.add(_step(t, 2))
    with pytest.raises(ValueError, match="divisible"):
        rb.sample(batch_size=3, sequence_length=2)


def test_dv3_cli_two_device_hbm_replay(tmp_path, monkeypatch):
    """End-to-end: DV3 over a 2-device mesh with buffer.device=True."""
    monkeypatch.chdir(tmp_path)
    from sheeprl_tpu.cli import run

    run(
        overrides=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "dry_run=True",
            "metric.log_level=0",
            "checkpoint.save_last=False",
            "fabric.devices=2",
            "buffer.device=True",
            "algo.learning_starts=0",
            "algo.per_rank_sequence_length=1",
            "algo.per_rank_batch_size=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=2",
            "algo.world_model.recurrent_model.recurrent_state_size=8",
            "algo.world_model.representation_model.hidden_size=8",
            "algo.world_model.transition_model.hidden_size=8",
            "algo.world_model.discrete_size=2",
            "algo.world_model.stochastic_size=2",
            "algo.horizon=3",
        ]
    )
