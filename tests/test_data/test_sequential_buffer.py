import numpy as np
import pytest

from sheeprl_tpu.data.buffers import SequentialReplayBuffer


def _data(t, n):
    return {"observations": np.arange(t * n).reshape(t, n, 1).astype(np.float32)}


def test_sample_shape_and_contiguity():
    rb = SequentialReplayBuffer(16, 2)
    rb.add(_data(16, 2))
    s = rb.sample(3, n_samples=2, sequence_length=5)
    assert s["observations"].shape == (2, 5, 3, 1)
    obs = s["observations"]
    # consecutive elements along the sequence axis differ by n_envs (env stream stride)
    diffs = np.diff(obs[..., 0], axis=1)
    assert np.all((diffs == 2) | (diffs == 2 - 16 * 2))  # wraparound allowed


def test_sample_not_enough_data():
    rb = SequentialReplayBuffer(16, 1)
    rb.add(_data(4, 1))
    with pytest.raises(ValueError):
        rb.sample(1, sequence_length=10)


def test_sample_seq_longer_than_buffer():
    rb = SequentialReplayBuffer(8, 1)
    rb.add(_data(10, 1))
    with pytest.raises(ValueError):
        rb.sample(1, sequence_length=9)


def test_full_buffer_avoids_write_head():
    rb = SequentialReplayBuffer(8, 1)
    rb.add(_data(12, 1))  # full, pos=4
    s = rb.sample(128, sequence_length=3)
    seqs = s["observations"][..., 0]  # [n_samples, L, B]
    # valid start values: sequences must be increments of 1 (contiguous stream)
    diffs = np.diff(seqs, axis=1)
    assert np.all(diffs == 1)


def test_sample_next_obs_sequences():
    rb = SequentialReplayBuffer(16, 1)
    rb.add(_data(16, 1))
    s = rb.sample(4, sequence_length=4, sample_next_obs=True)
    np.testing.assert_allclose(
        s["next_observations"][..., 0] % 16, (s["observations"][..., 0] + 1) % 16
    )


def test_memmap_sequential(tmp_path):
    rb = SequentialReplayBuffer(16, 2, memmap=True, memmap_dir=tmp_path / "seq")
    rb.add(_data(16, 2))
    s = rb.sample(2, sequence_length=4)
    assert s["observations"].shape == (1, 4, 2, 1)
