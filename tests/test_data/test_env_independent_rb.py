import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer


def _data(t, n):
    return {"observations": np.arange(t * n).reshape(t, n, 1).astype(np.float32)}


def test_add_all_envs():
    rb = EnvIndependentReplayBuffer(8, n_envs=3)
    rb.add(_data(4, 3))
    assert all(not b.empty for b in rb.buffer)
    assert rb.buffer[0].n_envs == 1


def test_add_subset_indices():
    rb = EnvIndependentReplayBuffer(8, n_envs=3)
    rb.add(_data(4, 2), indices=[0, 2])
    assert rb.buffer[1].empty
    with pytest.raises(ValueError):
        rb.add(_data(4, 2), indices=[0])


def test_sample_concat():
    rb = EnvIndependentReplayBuffer(8, n_envs=2)
    rb.add(_data(8, 2))
    s = rb.sample(6)
    assert s["observations"].shape == (1, 6, 1)


def test_sample_sequential_cls():
    rb = EnvIndependentReplayBuffer(16, n_envs=2, buffer_cls=SequentialReplayBuffer)
    rb.add(_data(16, 2))
    s = rb.sample(4, sequence_length=5)
    assert s["observations"].shape == (1, 5, 4, 1)
    diffs = np.diff(s["observations"][..., 0], axis=1)
    assert np.all(diffs == 2)  # per-env streams are contiguous with stride n_envs


def test_memmap_env_independent(tmp_path):
    rb = EnvIndependentReplayBuffer(8, n_envs=2, memmap=True, memmap_dir=tmp_path / "ei")
    rb.add(_data(4, 2))
    assert all(rb.is_memmap)


def test_state_dict_roundtrip():
    rb = EnvIndependentReplayBuffer(8, n_envs=2)
    rb.add(_data(4, 2))
    state = rb.state_dict()
    rb2 = EnvIndependentReplayBuffer(8, n_envs=2)
    rb2.load_state_dict(state)
    np.testing.assert_array_equal(
        np.asarray(rb2.buffer[0]["observations"]), np.asarray(rb.buffer[0]["observations"])
    )
