"""DeviceRolloutBuffer: the HBM-resident on-policy rollout store.

Covers the contracts the PPO/A2C loops lean on: value parity with the host
path's float32 arrays, donation safety (a consumed rollout is never aliased by
the next iteration's donated writes), strict no-wraparound semantics, and the
de-layouted checkpoint state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data.rollout_buffer import DeviceRolloutBuffer

T, B = 4, 3


def _fill_one_rollout(rb, rng):
    """Write T rows of policy + env leaves; return the expected host arrays."""
    ref = {
        k: np.zeros((T, B, d), np.float32)
        for k, d in [("values", 1), ("actions", 2), ("state", 5), ("rewards", 1), ("dones", 1)]
    }
    for t in range(T):
        policy = {
            "values": rng.random((B, 1), dtype=np.float32),
            "actions": rng.random((B, 2), dtype=np.float32),
        }
        rb.add_policy({k: jnp.asarray(v) for k, v in policy.items()})
        env = {
            "state": rng.random((B, 5), dtype=np.float32),
            "rewards": rng.random((B, 1), dtype=np.float32),
            # uint8 like the loops' dones: must land as float32 (host-path parity)
            "dones": (rng.random((B, 1)) < 0.3).astype(np.uint8),
        }
        rb.add_env(env)
        for k, v in policy.items():
            ref[k][t] = v
        for k, v in env.items():
            ref[k][t] = v.astype(np.float32)
    return ref


def test_fill_and_rollout_bit_parity():
    rb = DeviceRolloutBuffer(T, B, device=jax.devices()[0])
    ref = _fill_one_rollout(rb, np.random.default_rng(0))
    assert rb.full and rb.step == T
    out = rb.rollout()
    assert set(out) == set(ref)
    for k in ref:
        assert out[k].shape == (T, B, ref[k].shape[-1])
        assert out[k].dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(out[k]), ref[k])


def test_rollout_host_is_numpy():
    rb = DeviceRolloutBuffer(T, B, device=jax.devices()[0])
    ref = _fill_one_rollout(rb, np.random.default_rng(1))
    host = rb.rollout_host()
    for k in ref:
        assert isinstance(host[k], np.ndarray)
        np.testing.assert_array_equal(host[k], ref[k])


def test_donation_safety_consumed_rollout_never_aliased():
    """rollout() transfers ownership: the consumer's arrays must stay readable
    and unchanged while the NEXT iteration's donated writes fill new storage."""
    rb = DeviceRolloutBuffer(T, B, device=jax.devices()[0])
    ref = _fill_one_rollout(rb, np.random.default_rng(2))
    first = rb.rollout()
    for t in range(T):
        rb.add_policy({"values": jnp.ones((B, 1)), "actions": jnp.ones((B, 2))})
        rb.add_env(
            {
                "state": np.ones((B, 5), np.float32),
                "rewards": np.ones((B, 1), np.float32),
                "dones": np.zeros((B, 1), np.float32),
            }
        )
    # the first rollout still holds iteration-1 data (no use-after-donate)
    for k in ref:
        np.testing.assert_array_equal(np.asarray(first[k]), ref[k])
    second = rb.rollout()
    assert float(np.asarray(second["values"]).sum()) == T * B


def test_overfill_raises_instead_of_wrapping():
    rb = DeviceRolloutBuffer(T, B, device=jax.devices()[0])
    _fill_one_rollout(rb, np.random.default_rng(3))
    with pytest.raises(RuntimeError, match="full"):
        rb.add_env({"rewards": np.ones((B, 1), np.float32)})
    with pytest.raises(RuntimeError, match="full"):
        rb.add_policy({"values": jnp.ones((B, 1))})


def test_incomplete_rollout_raises():
    rb = DeviceRolloutBuffer(T, B, device=jax.devices()[0])
    rb.add_policy({"values": jnp.ones((B, 1))})
    rb.add_env({"rewards": np.ones((B, 1), np.float32)})
    with pytest.raises(RuntimeError, match="incomplete"):
        rb.rollout()


def test_reset_drops_partial_rollout():
    rb = DeviceRolloutBuffer(T, B, device=jax.devices()[0])
    rb.add_policy({"values": jnp.ones((B, 1))})
    rb.add_env({"rewards": np.ones((B, 1), np.float32)})
    rb.reset()
    assert rb.step == 0
    ref = _fill_one_rollout(rb, np.random.default_rng(4))
    np.testing.assert_array_equal(rb.rollout_host()["values"], ref["values"])


def test_leaf_shape_mismatch_raises():
    rb = DeviceRolloutBuffer(T, B, device=jax.devices()[0])
    rb.add_policy({"values": jnp.ones((B, 1))})
    rb.add_env({"rewards": np.ones((B, 1), np.float32)})
    with pytest.raises(ValueError, match="must be"):
        rb.add_env({"rewards": np.ones((B, 2), np.float32)})


def test_constructor_validation():
    with pytest.raises(ValueError):
        DeviceRolloutBuffer(0, B)
    with pytest.raises(ValueError):
        DeviceRolloutBuffer(T, 0)


def test_state_dict_roundtrip_resumes_mid_rollout():
    dev = jax.devices()[0]
    rb = DeviceRolloutBuffer(T, B, device=dev)
    for t in range(2):
        rb.add_policy({"values": jnp.full((B, 1), t + 1.0)})
        rb.add_env({"rewards": np.full((B, 1), t + 2.0, np.float32)})
    state = rb.state_dict()
    # de-layouted: checkpoints must be device-agnostic numpy
    assert all(isinstance(v, np.ndarray) for v in state["rollout"].values())
    assert state["t"] == 2
    rb2 = DeviceRolloutBuffer(T, B, device=dev).load_state_dict(state)
    assert rb2.step == 2
    for t in range(2):
        rb2.add_policy({"values": jnp.zeros((B, 1))})
        rb2.add_env({"rewards": np.zeros((B, 1), np.float32)})
    out = rb2.rollout_host()
    assert out["values"][0, 0, 0] == 1.0 and out["values"][1, 0, 0] == 2.0
    assert out["rewards"][0, 0, 0] == 2.0 and out["rewards"][1, 0, 0] == 3.0


def test_state_dict_shape_guard():
    state = DeviceRolloutBuffer(T, B, device=jax.devices()[0]).state_dict()
    assert state == {"rollout": None, "t": 0}
    rb = DeviceRolloutBuffer(T, B, device=jax.devices()[0])
    rb.add_policy({"values": jnp.ones((B, 1))})
    rb.add_env({"rewards": np.ones((B, 1), np.float32)})
    with pytest.raises(ValueError, match="configured"):
        DeviceRolloutBuffer(T + 1, B, device=jax.devices()[0]).load_state_dict(rb.state_dict())


def test_factory_backend_switch():
    from types import SimpleNamespace

    from sheeprl_tpu.data.buffers import ReplayBuffer
    from sheeprl_tpu.data.factory import buffer_backend, make_rollout_buffer

    class _Buf(dict):
        # OmegaConf-style: attribute access on top of .get()
        def __getattr__(self, k):
            try:
                return self[k]
            except KeyError:
                raise AttributeError(k)

    def cfg(backend=None, device=False, memmap=False, size=T):
        buf = _Buf(memmap=memmap, device=device, size=size)
        if backend is not None:
            buf["backend"] = backend
        return SimpleNamespace(buffer=buf, algo=SimpleNamespace(rollout_steps=T))

    rt = SimpleNamespace(player_device=jax.devices()[0], global_rank=0)
    assert buffer_backend(cfg()) == "host"
    assert buffer_backend(cfg(backend="device")) == "device"
    assert buffer_backend(cfg(device=True)) == "device"  # legacy alias
    # the alias wins over the (defaulted) backend=host so existing
    # buffer.device=True override lines keep selecting the HBM path
    assert buffer_backend(cfg(backend="host", device=True)) == "device"
    with pytest.raises(ValueError, match="backend"):
        buffer_backend(cfg(backend="hbm"))
    assert isinstance(make_rollout_buffer(cfg(backend="device"), rt, B, (), None), DeviceRolloutBuffer)
    assert isinstance(make_rollout_buffer(cfg(), rt, B, ("state",), None), ReplayBuffer)
    # memmap defaults True on the host path: backend=device alone must still
    # work (advisory warning, not an error)
    with pytest.warns(UserWarning, match="memmap"):
        rb = make_rollout_buffer(cfg(backend="device", memmap=True), rt, B, (), None)
    assert isinstance(rb, DeviceRolloutBuffer)
    with pytest.raises(ValueError, match="history"):
        make_rollout_buffer(cfg(backend="device", size=T + 1), rt, B, (), None)
