"""Baseline file: round-trip, justification preservation, apply split."""

import pytest

from sheeprl_tpu.analysis import baseline
from sheeprl_tpu.analysis.engine import Finding

pytestmark = pytest.mark.analysis


def _finding(rule="SA001", path="pkg/a.py", scope="train", match="x.item()", line=7):
    return Finding(
        rule=rule,
        path=path,
        line=line,
        col=4,
        message="host sync in traced code",
        scope=scope,
        match=match,
    )


def test_write_load_round_trip(tmp_path):
    path = str(tmp_path / "baseline.txt")
    findings = [_finding(), _finding(rule="SA004", scope="loopy", match="jax.jit(f)(x)")]
    written = baseline.write(findings, path=path)
    assert [e.justification for e in written] == [baseline.TODO_JUSTIFICATION] * 2

    loaded = baseline.load(path)
    assert [e.fingerprint for e in loaded] == [f.fingerprint() for f in sorted(
        findings, key=lambda f: (f.path, f.line, f.rule)
    )]


def test_write_preserves_justifications(tmp_path):
    path = str(tmp_path / "baseline.txt")
    f = _finding()
    baseline.write([f], path=path)
    justified = [
        baseline.BaselineEntry(
            rule=e.rule, path=e.path, scope=e.scope, match=e.match,
            justification="reviewed: the one unavoidable host sync",
        )
        for e in baseline.load(path)
    ]
    # regenerate from the same finding at a DIFFERENT line: fingerprint is
    # line-free, so the justification must survive
    moved = _finding(line=99)
    rewritten = baseline.write([moved], path=path, previous=justified)
    assert rewritten[0].justification == "reviewed: the one unavoidable host sync"
    assert baseline.load(path)[0].justification == "reviewed: the one unavoidable host sync"


def test_write_dedupes_same_fingerprint(tmp_path):
    path = str(tmp_path / "baseline.txt")
    entries = baseline.write([_finding(line=7), _finding(line=42)], path=path)
    assert len(entries) == 1


def test_apply_splits_unsuppressed_suppressed_stale():
    covered = _finding()
    uncovered = _finding(rule="SA002", scope="roll", match="jax.random.normal(key)")
    entries = [
        baseline.BaselineEntry(
            rule=covered.rule, path=covered.path, scope=covered.scope,
            match=covered.match, justification="ok",
        ),
        baseline.BaselineEntry(
            rule="SA003", path="gone.py", scope="x", match="y", justification="stale",
        ),
    ]
    unsuppressed, suppressed, stale = baseline.apply([covered, uncovered], entries)
    assert unsuppressed == [uncovered]
    assert suppressed == [covered]
    assert [e.match for e in stale] == ["y"]


def test_load_skips_comments_and_rejects_malformed(tmp_path):
    path = tmp_path / "baseline.txt"
    path.write_text("# comment\n\nSA001 | a.py | fn | x.item() | why\n")
    entries = baseline.load(str(path))
    assert len(entries) == 1 and entries[0].justification == "why"

    path.write_text("SA001 | a.py | fn\n")
    with pytest.raises(ValueError):
        baseline.load(str(path))


def test_missing_file_loads_empty(tmp_path):
    assert baseline.load(str(tmp_path / "nope.txt")) == []


def test_checked_in_baseline_is_fully_justified():
    entries = baseline.load()
    for e in entries:
        assert e.justification and e.justification != baseline.TODO_JUSTIFICATION, (
            f"baseline row without a real justification: {e.to_line()}"
        )
