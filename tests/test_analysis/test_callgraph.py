"""jit-reachability call graph: entry detection, edge resolution, closure."""

import os
import textwrap

import pytest

from sheeprl_tpu.analysis import Analyzer
from sheeprl_tpu.analysis.callgraph import (
    FALLBACK_JIT_ENTRY_WRAPPERS,
    load_jit_entry_wrappers,
)

from tests.test_analysis.conftest import PACKAGE_DIR

pytestmark = pytest.mark.analysis


def test_wrappers_load_statically_from_compile_py():
    wrappers = load_jit_entry_wrappers(PACKAGE_DIR)
    assert "jit" in wrappers and "guarded_jit" in wrappers and "shard_map" in wrappers
    # the fallback mirrors core/compile.py's exported list; drift between the
    # two means one side was edited without the other
    assert set(wrappers) == set(FALLBACK_JIT_ENTRY_WRAPPERS)


def test_wrappers_fall_back_without_compile_py(tmp_path):
    assert load_jit_entry_wrappers(str(tmp_path)) == FALLBACK_JIT_ENTRY_WRAPPERS


def _write_tree(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text(
        textwrap.dedent(
            """\
            import jax
            from pkg.b import helper


            def train(x):
                return helper(x)


            def never_jitted(x):
                return helper(x) + 1


            step = jax.jit(train, donate_argnums=(0,))
            """
        )
    )
    (pkg / "b.py").write_text(
        textwrap.dedent(
            """\
            import jax
            from functools import partial


            def helper(x):
                return inner(x)


            def inner(x):
                return x


            def cold(x):
                return x


            @jax.jit
            def dec_entry(x):
                return cold_callee(x)


            def cold_callee(x):
                return x


            @partial(jax.jit, static_argnums=(1,))
            def partial_entry(x, n):
                return x


            class Stepper:
                @jax.jit
                def step(self, x):
                    return self.helper_m(x)

                def helper_m(self, x):
                    return x
            """
        )
    )
    return tmp_path


def test_entry_points_and_closure(tmp_path):
    root = _write_tree(tmp_path)
    cg = Analyzer([str(root)], root=str(root), package_dir=PACKAGE_DIR).callgraph

    # entry via wrapper call argument: jax.jit(train)
    assert cg.is_traced("pkg/a.py", "train")
    # cross-module edge train -> pkg.b.helper -> inner
    assert cg.is_traced("pkg/b.py", "helper")
    assert cg.is_traced("pkg/b.py", "inner")
    # entry via decorator / @partial(jax.jit, ...)
    assert cg.is_traced("pkg/b.py", "dec_entry")
    assert cg.is_traced("pkg/b.py", "cold_callee")
    assert cg.is_traced("pkg/b.py", "partial_entry")
    # decorated method, qualified by class
    assert cg.is_traced("pkg/b.py", "Stepper.step")

    # not reachable from any jit entry
    assert not cg.is_traced("pkg/a.py", "never_jitted")
    assert not cg.is_traced("pkg/b.py", "cold")

    entries = cg.entry_points
    assert ("pkg/a.py", "train") in entries
    assert ("pkg/b.py", "dec_entry") in entries
    assert ("pkg/a.py", "never_jitted") not in entries


def test_traced_functions_per_module(tmp_path):
    root = _write_tree(tmp_path)
    cg = Analyzer([str(root)], root=str(root), package_dir=PACKAGE_DIR).callgraph
    names = {fi.qualname for fi in cg.traced_functions("pkg/b.py")}
    assert {"helper", "inner", "dec_entry"} <= names
    assert "cold" not in names
    for fi in cg.traced_functions("pkg/b.py"):
        assert fi.module_rel == "pkg/b.py"
        assert fi.simple_name == fi.qualname.rsplit(".", 1)[-1]


def test_real_tree_has_traced_entry_points():
    repo_root = os.path.dirname(PACKAGE_DIR)
    cg = Analyzer([PACKAGE_DIR], root=repo_root, package_dir=PACKAGE_DIR).callgraph
    assert cg.entry_points, "real tree should expose at least one jit entry"
