import os

import pytest

FIXTURE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PACKAGE_DIR = os.path.join(REPO_ROOT, "sheeprl_tpu")


def collect_markers(path):
    """(line, rule) pairs for every `# VIOLATION:SA00x` marker in a fixture."""
    expected = []
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            if "# VIOLATION:" in line:
                rule = line.split("# VIOLATION:", 1)[1].split()[0].strip()
                expected.append((lineno, rule))
    return expected


@pytest.fixture(scope="session")
def fixture_dir():
    return FIXTURE_DIR


@pytest.fixture(scope="session")
def package_dir():
    return PACKAGE_DIR


@pytest.fixture(scope="session")
def repo_root():
    return REPO_ROOT
