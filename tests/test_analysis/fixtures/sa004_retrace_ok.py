"""SA004 near-misses — static branches, hoisted jit, hashable statics."""
import jax
import jax.numpy as jnp


def traced_branch_ok(x, n: int, reduction: str = "mean"):
    if x is None:  # identity check: static
        return jnp.zeros(())
    if n > 3:  # annotated python int: static under trace
        x = x * 2.0
    if reduction == "mean":  # string dispatch: static
        return jnp.mean(x)
    return jnp.where(x > 0, jnp.log(jnp.abs(x)), 0.0)  # traced select, no branch


branchy = jax.jit(traced_branch_ok, static_argnums=(1, 2))


def loop_ok(f, xs):
    g = jax.jit(f)  # hoisted out of the loop
    out = []
    for x in xs:
        out.append(g(x))
    return out


def static_ok(f):
    g = jax.jit(f, static_argnums=(1,))
    return g(1.0, (4, 5))  # tuple: hashable
