"""SA005 near-misses — registered names, valid actions, dynamic specs."""
import os

from sheeprl_tpu.core import failpoints


def drill(n, name):
    failpoints.failpoint("ckpt.pre_fsync")
    failpoints.configure(f"preempt.iteration:signal:SIGTERM:hit={n}")
    failpoints.failpoint(name)  # dynamic name: not statically checkable
    with failpoints.active("env.step:raise:boom:hit=2"):
        pass


def env_drill():
    env = dict(os.environ)
    env["SHEEPRL_TPU_FAILPOINTS"] = failpoints.spec_entry(
        "orchestrate.inject", "fire", trigger="every=10"
    )
    return env
