"""SA006 near-misses — valid keys, sub-config aliases, method/underscore stops."""


def train(cfg, sub_cfg):
    a = cfg.algo.name
    b = cfg.env.id
    c = cfg.mlp_layers  # unknown ROOT child: sub-config alias, skipped
    d = sub_cfg.whatever.deep.chain  # not `cfg`: skipped
    e = cfg.algo.get("total_steps")  # dict method: validation stops
    f = cfg.algo._target_  # underscore segment: validation stops
    return a, b, c, d, e, f
