"""SA003 near-misses — donated carries rebound in the same statement."""
import jax


def run(train, state, batch):
    step = jax.jit(train, donate_argnums=(0,))
    state = step(state, batch)  # rebound from the result: alive again
    return state["loss"]


def loop_run(train, state, batches):
    step = jax.jit(train, donate_argnums=(0,))
    for batch in batches:
        state = step(state, batch)  # carry threads through the loop
    return state


def no_donation(train, state, batch):
    step = jax.jit(train)  # nothing donated
    out = step(state, batch)
    return out, state["loss"]
