"""SA001 fixture — host syncs inside a jit-traced function (all flagged)."""
import jax
import jax.numpy as jnp
import numpy as np


def traced_step(x, y):
    val = x.item()  # VIOLATION:SA001
    jax.device_get(x)  # VIOLATION:SA001
    print("step", val)  # VIOLATION:SA001
    host = np.asarray(x + y)  # VIOLATION:SA001
    flag = float(x)  # VIOLATION:SA001
    return jnp.sum(x) + flag, host


step = jax.jit(traced_step)
