"""SA002 near-misses — split-before-use discipline, none may flag."""
import jax


def split_before_use(seed):
    key = jax.random.PRNGKey(seed)
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, (4,))
    key, sub = jax.random.split(key)
    b = jax.random.normal(sub, (4,))
    return a + b


def per_iteration_fold(seed, xs):
    key = jax.random.PRNGKey(seed)
    total = 0.0
    for i, x in enumerate(xs):
        k = jax.random.fold_in(key, i)
        total = total + x * jax.random.uniform(k)
    return total


def threaded(seed, player, obs_seq):
    # `..., key = f(..., key)`: the callee returns the split successor
    key = jax.random.PRNGKey(seed)
    outs = []
    for obs in obs_seq:
        action, key = player.get_actions(obs, key)
        outs.append(action)
    return outs


def branch_use(seed, flag):
    # mutually exclusive branches each consume once: legal
    key = jax.random.PRNGKey(seed)
    if flag:
        return jax.random.normal(key, (2,))
    return jax.random.uniform(key)
