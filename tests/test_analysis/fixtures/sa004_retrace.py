"""SA004 fixture — retrace hazards (traced branch, jit-in-loop, unhashable static)."""
import jax
import jax.numpy as jnp


def traced_branch(x):
    if x > 0:  # VIOLATION:SA004
        return jnp.log(x)
    return jnp.log(-x)


branchy = jax.jit(traced_branch)


def loopy(f, xs):
    out = []
    for x in xs:
        out.append(jax.jit(f)(x))  # VIOLATION:SA004
    return out


def static_list(f):
    g = jax.jit(f, static_argnums=(1,))
    return g(1.0, [4, 5])  # VIOLATION:SA004
