"""SA006 fixture — cfg key drift (must be placed under sheeprl_tpu/algos/)."""


def train(cfg):
    lr = cfg.algo.optimizer.lr
    steps = cfg.algo.total_steps
    bad = cfg.algo.rolout_steps  # VIOLATION:SA006 (typo'd key)
    worse = cfg.checkpoint.evrey  # VIOLATION:SA006 (typo'd key)
    return lr, steps, bad, worse
