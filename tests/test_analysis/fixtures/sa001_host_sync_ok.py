"""SA001 near-misses — none of these may flag."""
import jax
import jax.numpy as jnp
import numpy as np


def host_loop(x):
    # NOT jit-reachable: host pulls are the point of this function
    val = x.item()
    print("logging", val)
    return np.asarray(x)


def traced_step(x):
    jax.debug.print("x={x}", x=x)  # tracing-safe print
    shape = x.shape  # static metadata, no sync
    zeros = np.zeros((4,))  # numpy on a NON-traced value
    return jnp.sum(x) + zeros.sum(), shape


step = jax.jit(traced_step)
