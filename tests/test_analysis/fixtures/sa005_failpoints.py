"""SA005 fixture — failpoint name/action drift vs KNOWN_FAILPOINTS."""
import os

from sheeprl_tpu.core import failpoints


def drill():
    failpoints.failpoint("ckpt.pre_fsnyc")  # VIOLATION:SA005 (typo'd name)
    failpoints.configure("no.such_point:raise")  # VIOLATION:SA005 (unknown name)
    failpoints.configure("transport.player_crash:explode")  # VIOLATION:SA005 (unknown action)


def env_drill():
    env = dict(os.environ)
    env["SHEEPRL_TPU_FAILPOINTS"] = "reload.canray:raise:hit=1"  # VIOLATION:SA005
    return env
