"""SA002 fixture — PRNG key reuse (double consumption + loop reuse)."""
import jax


def double_use(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, (4,))
    b = jax.random.normal(key, (4,))  # VIOLATION:SA002
    return a + b


def loop_reuse(seed, xs):
    key = jax.random.PRNGKey(seed)
    total = 0.0
    for x in xs:
        total = total + x * jax.random.uniform(key)  # VIOLATION:SA002
    return total
