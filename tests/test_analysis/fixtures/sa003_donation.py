"""SA003 fixture — reading a buffer after passing it at a donated position."""
import jax


def run(train, state, batch):
    step = jax.jit(train, donate_argnums=(0,))
    new_state = step(state, batch)
    loss = state["loss"]  # VIOLATION:SA003
    return new_state, loss


def loop_run(train, state, batches):
    step = jax.jit(train, donate_argnums=(0,))
    for batch in batches:
        out = step(state, batch)  # VIOLATION:SA003 (iteration 2 reads donated state)
    return out
