"""Per-rule fixture tests: every rule catches its violation fixture at the
exact marked lines, and stays silent on the paired near-miss fixture."""

import os
import shutil

import pytest

from sheeprl_tpu.analysis import Analyzer

from tests.test_analysis.conftest import FIXTURE_DIR, PACKAGE_DIR, collect_markers

pytestmark = pytest.mark.analysis

# rule id -> (violation fixture, ok fixture, target rel-path dir inside tmp).
# SA006 only checks algos/serve/orchestrate paths; SA005 skips test-ish paths —
# fixtures are copied to a neutral (or rule-required) location before analyzing.
CASES = {
    "SA001": ("sa001_host_sync.py", "sa001_host_sync_ok.py", "pkg"),
    "SA002": ("sa002_prng.py", "sa002_prng_ok.py", "pkg"),
    "SA003": ("sa003_donation.py", "sa003_donation_ok.py", "pkg"),
    "SA004": ("sa004_retrace.py", "sa004_retrace_ok.py", "pkg"),
    "SA005": ("sa005_failpoints.py", "sa005_failpoints_ok.py", "pkg"),
    "SA006": ("sa006_config_keys.py", "sa006_config_keys_ok.py", "sheeprl_tpu/algos"),
}


def _analyze_fixture(tmp_path, fixture_name, target_dir, rule_id):
    src = os.path.join(FIXTURE_DIR, fixture_name)
    dst_dir = os.path.join(str(tmp_path), target_dir)
    os.makedirs(dst_dir, exist_ok=True)
    dst = os.path.join(dst_dir, fixture_name)
    shutil.copyfile(src, dst)
    analyzer = Analyzer([str(tmp_path)], root=str(tmp_path), package_dir=PACKAGE_DIR)
    return analyzer.run(rule_ids=[rule_id])


@pytest.mark.parametrize("rule_id", sorted(CASES))
def test_rule_flags_violation_fixture(tmp_path, rule_id):
    violation, _, target_dir = CASES[rule_id]
    expected = collect_markers(os.path.join(FIXTURE_DIR, violation))
    assert expected, f"fixture {violation} has no VIOLATION markers"
    findings = _analyze_fixture(tmp_path, violation, target_dir, rule_id)
    got = sorted((f.line, f.rule) for f in findings)
    assert got == sorted(expected), (
        f"{rule_id} findings {got} != expected markers {sorted(expected)}; "
        f"messages: {[f.message for f in findings]}"
    )
    # every finding anchors path:line to the analyzed file
    for f in findings:
        assert f.path.endswith(violation)
        assert f.rule == rule_id
        assert f.line > 0 and f.message


@pytest.mark.parametrize("rule_id", sorted(CASES))
def test_rule_silent_on_near_miss_fixture(tmp_path, rule_id):
    _, ok, target_dir = CASES[rule_id]
    findings = _analyze_fixture(tmp_path, ok, target_dir, rule_id)
    assert findings == [], (
        f"{rule_id} false positives on {ok}: "
        f"{[(f.line, f.message) for f in findings]}"
    )


def test_findings_sorted_and_fingerprint_stable(tmp_path):
    violation, _, target_dir = CASES["SA001"]
    f1 = _analyze_fixture(tmp_path, violation, target_dir, "SA001")
    f2 = _analyze_fixture(tmp_path, violation, target_dir, "SA001")
    assert [f.fingerprint() for f in f1] == [f.fingerprint() for f in f2]
    assert f1 == sorted(f1, key=lambda f: (f.path, f.line, f.rule))
