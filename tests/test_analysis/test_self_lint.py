"""Tier-1 self-lint: the real tree carries zero unsuppressed findings, and a
seeded violation in a real module is caught with the right rule and line."""

import json
import os
import shutil
import subprocess
import sys
import textwrap
import time

import pytest

from sheeprl_tpu.analysis import Analyzer, baseline

from tests.test_analysis.conftest import PACKAGE_DIR, REPO_ROOT, collect_markers

pytestmark = pytest.mark.analysis

_SELF_LINT_BUDGET_S = 20.0

# one statement per rule, each tagged with the marker collect_markers reads
_SEEDED_SOURCE = textwrap.dedent(
    """\
    import os

    import jax
    from jax import random


    def traced_step(x, y):
        bad = x.item()  # VIOLATION:SA001
        key = random.PRNGKey(0)
        a = random.normal(key)
        b = random.normal(key)  # VIOLATION:SA002
        if x > 0:  # VIOLATION:SA004
            y = y + 1
        return bad + a + b + y


    step = jax.jit(traced_step, donate_argnums=(0,))


    def driver(state, batch, cfg):
        out = step(state, batch)
        loss = state.mean()  # VIOLATION:SA003
        os.environ["SHEEPRL_TPU_FAILPOINTS"] = "ckpt.pre_fsnyc:raise"  # VIOLATION:SA005
        return out, loss, cfg.algo.rolout_steps  # VIOLATION:SA006
    """
)
_SEEDED_REL = "sheeprl_tpu/algos/ppo/_seeded_violation.py"


def _lint(paths, root, package_dir):
    findings = Analyzer(paths, root=root, package_dir=package_dir).run()
    unsuppressed, suppressed, stale = baseline.apply(findings, baseline.load())
    return unsuppressed, suppressed, stale


def test_real_tree_is_clean_within_budget():
    t0 = time.monotonic()
    unsuppressed, _, stale = _lint(
        [PACKAGE_DIR, os.path.join(REPO_ROOT, "scripts")],
        root=REPO_ROOT,
        package_dir=PACKAGE_DIR,
    )
    elapsed = time.monotonic() - t0
    assert unsuppressed == [], "unsuppressed findings:\n" + "\n".join(
        f"  {f.location()}: {f.rule} {f.message}" for f in unsuppressed
    )
    assert stale == [], "stale baseline rows (fix was landed — delete them):\n" + "\n".join(
        f"  {e.to_line()}" for e in stale
    )
    assert elapsed < _SELF_LINT_BUDGET_S, f"self-lint took {elapsed:.1f}s"


def test_seeded_violations_fail_the_lint(tmp_path):
    # copy the real package so baseline fingerprints (rooted at sheeprl_tpu/)
    # still apply, then plant one violation per rule in a real algo dir
    copy_pkg = str(tmp_path / "sheeprl_tpu")
    shutil.copytree(
        PACKAGE_DIR, copy_pkg, ignore=shutil.ignore_patterns("__pycache__")
    )
    seeded_path = os.path.join(str(tmp_path), _SEEDED_REL)
    with open(seeded_path, "w", encoding="utf-8") as f:
        f.write(_SEEDED_SOURCE)

    unsuppressed, _, _ = _lint([copy_pkg], root=str(tmp_path), package_dir=copy_pkg)
    seeded = sorted((f.line, f.rule) for f in unsuppressed if f.path == _SEEDED_REL)
    expected = sorted(collect_markers(seeded_path))
    assert seeded == expected, (
        f"seeded violations {expected} vs detected {seeded}; all unsuppressed: "
        f"{[(f.path, f.line, f.rule) for f in unsuppressed]}"
    )
    # nothing else in the copied tree may surface: the seeds are the only delta
    assert all(f.path == _SEEDED_REL for f in unsuppressed)


def test_cli_exits_zero_on_clean_tree():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu.analysis", "--format", "json"],
        cwd=REPO_ROOT,
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["suppressed"], "baseline suppressions should be reported"
