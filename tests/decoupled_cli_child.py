"""Child process for the CLI-driven cross-host decoupled tests (test_multihost.py).

Run as: python tests/decoupled_cli_child.py <coordinator_port> <process_id> <num_processes> <tmpdir> [algo]

Unlike decoupled_child.py (which drives the transport primitives by hand), this
child goes through the REAL CLI entrypoint — ``sheeprl_tpu.cli.run`` with
``exp=ppo_decoupled``/``exp=sac_decoupled`` and the multihost fabric flags —
proving the cross-host actor-learner path is reachable exactly the way the
reference's multi-node launch is (``sheeprl exp=ppo_decoupled`` under torchrun,
/root/reference/sheeprl/algos/ppo/ppo_decoupled.py:623-670). jax.distributed is
initialized by the Runtime FROM THE CONFIG, not by this script.

A 2-process world with 2 CPU devices each: global device 0 (process 0) plays,
the other 3 devices form the cross-process trainer mesh. One dry_run iteration
trains end-to-end and writes the final checkpoint on the player process.
Prints one JSON line with the run's observable outcomes.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = [f for f in os.environ.get("XLA_FLAGS", "").split() if "host_platform_device_count" not in f]
flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    port, pid, nproc, tmpdir = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    algo = sys.argv[5] if len(sys.argv) > 5 else "ppo_decoupled"
    if os.environ.get("XH_DEBUG"):  # dump a stack if a collective wedges this process
        import faulthandler

        faulthandler.dump_traceback_later(int(os.environ["XH_DEBUG"]), exit=True, file=sys.stderr)
    os.chdir(tmpdir)

    from sheeprl_tpu.cli import run

    common = [
        "dry_run=True",
        "env=dummy",
        "env.num_envs=3",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=2",
        "fabric.multihost=True",
        f"fabric.coordinator_address=localhost:{port}",
        f"fabric.num_processes={nproc}",
        f"fabric.process_id={pid}",
        "metric.log_level=0",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        "algo.mlp_keys.encoder=[state]",
        "algo.run_test=False",
    ]
    if algo == "ppo_decoupled":
        args = common + [
            "exp=ppo_decoupled",
            "env.id=discrete_dummy",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",  # x3 trainer devices = n_data (4 steps x 3 envs)
            "algo.update_epochs=1",
            "algo.cnn_keys.encoder=[]",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
        ]
    else:
        args = common + [
            "exp=sac_decoupled",
            "env.id=continuous_dummy",
            "algo.per_rank_batch_size=2",
            "algo.learning_starts=0",
            "algo.hidden_size=8",
            "buffer.size=64",
        ]
    run(overrides=args)

    ckpts = []
    for root, _, files in os.walk(os.path.join(tmpdir, "logs")):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    print(json.dumps({"pid": pid, "done": True, "n_ckpts": len(ckpts)}))


if __name__ == "__main__":
    main()
