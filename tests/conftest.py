"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax initializes.

Mirrors the reference's CPU-Gloo multi-process tests (tests/test_algos/test_algos.py
`devices` fixture + LT_DEVICES): here multi-device paths run on one host via
``--xla_force_host_platform_device_count=8``.
"""

import os
import sys

# The image pre-sets JAX_PLATFORMS=axon (the TPU tunnel) AND its sitecustomize calls
# jax.config.update("jax_platforms", "axon,cpu") at interpreter start, so overriding
# the env var is not enough — the config itself must be re-pointed at cpu before any
# backend is initialized.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", f"tests must run on the CPU mesh, got {jax.devices()}"
assert jax.device_count() == 8, f"expected 8 virtual CPU devices, got {jax.device_count()}"

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_metric_state():
    from sheeprl_tpu.utils.metric import MetricAggregator
    from sheeprl_tpu.utils.timer import timer

    yield
    MetricAggregator.disabled = False
    timer.disabled = False
    timer.reset()


@pytest.fixture()
def standard_args():
    return [
        "dry_run=True",
        "env=dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.devices=1",
        "metric.log_level=0",
        "checkpoint.save_last=False",
    ]
