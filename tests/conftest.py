"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax initializes.

Mirrors the reference's CPU-Gloo multi-process tests (tests/test_algos/test_algos.py
`devices` fixture + LT_DEVICES): here multi-device paths run on one host via
``--xla_force_host_platform_device_count=8``.
"""

import os
import sys

# The image pre-sets JAX_PLATFORMS=axon (the TPU tunnel) AND its sitecustomize calls
# jax.config.update("jax_platforms", "axon,cpu") at interpreter start, so overriding
# the env var is not enough — the config itself must be re-pointed at cpu before any
# backend is initialized.
os.environ["JAX_PLATFORMS"] = "cpu"
# Cache even sub-second kernels (jax's default threshold is 1s): the suite's
# many subprocess CLI drills recompile dozens of tiny CPU kernels each, and
# serving them from the shared persistent cache keeps the suite inside its
# wall-clock budget. setdefault so an explicit caller choice still wins.
os.environ.setdefault("SHEEPRL_TPU_COMP_CACHE_MIN_SECS", "0")
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", f"tests must run on the CPU mesh, got {jax.devices()}"
assert jax.device_count() == 8, f"expected 8 virtual CPU devices, got {jax.device_count()}"

import signal  # noqa: E402
import threading  # noqa: E402

import pytest  # noqa: E402

# Per-test wall-clock limits without the pytest-timeout dependency (reference gates
# test_algos.py at 60-180 s via pytest-timeout, tests/conftest.py:71-76; the virtual
# 8-device CPU mesh compiles slower, hence the larger default).
_ALGO_TEST_DEFAULT_TIMEOUT = 600


def pytest_configure(config):
    config.addinivalue_line("markers", "timeout(seconds): per-test wall-clock limit (SIGALRM)")
    config.addinivalue_line(
        "markers",
        "faults: fault-injection drills (failpoint registry, chaos/transport smokes); "
        "select with `-m faults`, e.g. before touching checkpoint or transport code",
    )
    config.addinivalue_line(
        "markers",
        "ingraph: in-graph vectorized env backend (envs/ingraph/) — dynamics parity "
        "against Gymnasium, zero-transfer rollout guarantees, and the smoke drill; "
        "select with `-m ingraph` before touching envs/ingraph or the fused collector",
    )
    config.addinivalue_line(
        "markers",
        "telemetry: cross-plane telemetry (sheeprl_tpu/telemetry/) — span tracer, "
        "metrics fabric, device introspection, trace-id propagation; select with "
        "`-m telemetry` before touching telemetry/ or its instrumentation seams",
    )
    config.addinivalue_line(
        "markers",
        "kernels: fused Pallas RSSM step kernels (sheeprl_tpu/ops/pallas/) — interpret "
        "bit-parity vs the reference formulation, custom_vjp gradient parity, dispatch/"
        "VMEM-gate units, and the flax-fallback drill; select with `-m kernels` before "
        "touching ops/pallas or the RSSM dispatch seams",
    )
    config.addinivalue_line(
        "markers",
        "analysis: the JAX-invariant static analyzer (sheeprl_tpu/analysis/) — rule "
        "fixtures, call-graph reachability, baseline round-trips, and the tree-wide "
        "self-lint; select with `-m analysis` (or run scripts/lint.sh) before "
        "touching analysis/ or code the self-lint covers",
    )
    config.addinivalue_line(
        "markers",
        "fleet: the replica-fleet serving plane (serve/fleet.py + serve/router.py) — "
        "supervisor respawns and epoch fencing, failover/deadline relays, rolling "
        "certified deploys, and the preemption fan-out drill; select with `-m fleet` "
        "before touching the fleet supervisor, the router, or their drain contracts",
    )
    config.addinivalue_line(
        "markers",
        "mesh: overlap-scheduled mesh training (parallel/handoff.py + parallel/overlap.py "
        "+ the HLO collective auditor) — one-put-per-shard transfer-guard pins, "
        "microbatched gradient bit-parity on the 8-device virtual mesh, collective "
        "capture/diff gating, and the handoff/grad-sync chaos drills; select with "
        "`-m mesh` before touching the handoff, the accumulation scan, or the auditor",
    )
    config.addinivalue_line(
        "markers",
        "slow: long-running end-to-end smokes excluded from the tier-1 `-m 'not slow'` "
        "sweep; run explicitly (e.g. `-m slow`) before shipping changes they cover",
    )


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    marker = item.get_closest_marker("timeout")
    seconds = int(marker.args[0]) if marker and marker.args else 0
    if not seconds and "test_algos.py" in str(getattr(item, "fspath", "")):
        seconds = _ALGO_TEST_DEFAULT_TIMEOUT
    use_alarm = (
        seconds > 0
        and hasattr(signal, "SIGALRM")
        and threading.current_thread() is threading.main_thread()
    )
    if not use_alarm:
        return (yield)

    def _on_timeout(signum, frame):
        raise TimeoutError(f"test exceeded the {seconds}s wall-clock limit")

    old = signal.signal(signal.SIGALRM, _on_timeout)
    signal.alarm(seconds)
    try:
        return (yield)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(autouse=True)
def _reset_metric_state():
    from sheeprl_tpu.telemetry import trace
    from sheeprl_tpu.utils.metric import MetricAggregator
    from sheeprl_tpu.utils.timer import timer

    yield
    MetricAggregator.disabled = False
    timer.disabled = False
    timer.reset()
    # a test that configured the span tracer must not leak it (or its
    # SHEEPRL_TPU_TRACE env mirror) into tests asserting disabled-mode behavior
    trace.disable()


@pytest.fixture()
def standard_args():
    return [
        "dry_run=True",
        "env=dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.devices=1",
        "metric.log_level=0",
        "checkpoint.save_last=False",
    ]
