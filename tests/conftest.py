"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax initializes.

Mirrors the reference's CPU-Gloo multi-process tests (tests/test_algos/test_algos.py
`devices` fixture + LT_DEVICES): here multi-device paths run on one host via
``--xla_force_host_platform_device_count=8``.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_metric_state():
    from sheeprl_tpu.utils.metric import MetricAggregator
    from sheeprl_tpu.utils.timer import timer

    yield
    MetricAggregator.disabled = False
    timer.disabled = False
    timer.reset()


@pytest.fixture()
def standard_args():
    return [
        "exp=dummy",
        "dry_run=True",
        "env=dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.devices=1",
        "metric.log_level=0",
        "checkpoint.save_last=False",
    ]
