"""Child process for the cross-process decoupled PPO test (test_multihost.py).

Run as: python tests/decoupled_child.py <coordinator_port> <process_id> <num_processes> <tmpdir>

A 2-process world with 2 CPU devices each (4 global devices). The decoupled
role split is taken over the GLOBAL device set via split_runtime_crosshost:
global device 0 (on process 0) is the player, the remaining 3 devices — one on
process 0 and both of process 1 — form the cross-process trainer mesh. One full
decoupled PPO round runs twice:

  player process collects a (fabricated, seeded) host rollout
    -> CrossHostTransport.rollout_to_trainers (one device broadcast collective
       + local placement on the trainer mesh; the reference pipes this through
       torch scatter_object_list, ppo_decoupled.py:294-310)
    -> the REAL jitted PPO optimization phase (make_train_fn) over the
       3-device cross-process mesh
    -> CrossHostTransport.params_to_player: local D2D refresh onto the player
       chip (reference: flattened-vector NCCL broadcast, :550-554)

Prints one JSON line; the parent asserts params actually changed, all
processes hold bit-identical post-update params, and the player refresh
matches the trainer params exactly.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = [f for f in os.environ.get("XLA_FLAGS", "").split() if "host_platform_device_count" not in f]
flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheeprl_tpu.core.runtime import enable_cpu_collectives  # noqa: E402

enable_cpu_collectives()  # gloo: CPU cross-process collectives (before backend init)


def main() -> None:
    port, pid, nproc = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    if os.environ.get("XH_DEBUG"):  # dump a stack if a collective wedges this process
        import faulthandler

        faulthandler.dump_traceback_later(int(os.environ["XH_DEBUG"]), exit=True, file=sys.stderr)
    jax.distributed.initialize(f"localhost:{port}", num_processes=nproc, process_id=pid)

    import gymnasium as gym
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import make_train_fn
    from sheeprl_tpu.config import instantiate
    from sheeprl_tpu.config.loader import load_config
    from sheeprl_tpu.core.runtime import Runtime
    from sheeprl_tpu.parallel import split_runtime_crosshost
    from sheeprl_tpu.utils.optim import with_clipping

    runtime = Runtime(accelerator="cpu", devices=jax.device_count(), multihost=True)
    player_rt, trainer_rt, transport = split_runtime_crosshost(runtime)
    assert trainer_rt.world_size == 3, trainer_rt.world_size
    assert transport.is_player_process == (pid == 0)

    rollout_steps, n_envs = 4, 3  # n_data = 12 = one global minibatch (4 * 3 trainers)
    cfg = load_config(
        overrides=[
            "exp=ppo",
            "env=dummy",
            "env.num_envs=3",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            f"algo.rollout_steps={rollout_steps}",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.mlp_layers=1",
            "algo.dense_units=8",
            "fabric.devices=2",
        ]
    )
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-1, 1, (5,), np.float32)})
    actions_dim = (4,)
    agent, params, _player = build_agent(trainer_rt, actions_dim, False, cfg, obs_space)
    tx = with_clipping(instantiate(dict(cfg.algo.optimizer))(), cfg.algo.max_grad_norm)
    # params are already trainer-mesh-replicated globals, so optax init's eager
    # zeros_like inherits that placement — re-placing through device_put would
    # trigger jax's per-leaf cross-process equality allgather for nothing
    opt_state = tx.init(params)
    n_data = rollout_steps * n_envs
    train_fn = make_train_fn(agent, tx, cfg, trainer_rt, n_data, ["state"], [])

    params_before = np.concatenate(
        [np.asarray(leaf.addressable_data(0)).ravel() for leaf in jax.tree_util.tree_leaves(params)]
    )

    rng = np.random.default_rng(7)  # both processes build templates; only pid 0's VALUES matter
    for round_i in range(2):
        if transport.is_player_process:
            host_data = {
                "state": rng.standard_normal((rollout_steps, n_envs, 5), dtype=np.float32),
                "actions": np.eye(4, dtype=np.float32)[rng.integers(0, 4, (rollout_steps, n_envs))],
                "logprobs": rng.standard_normal((rollout_steps, n_envs, 1), dtype=np.float32),
                "values": rng.standard_normal((rollout_steps, n_envs, 1), dtype=np.float32),
                "rewards": rng.standard_normal((rollout_steps, n_envs, 1), dtype=np.float32),
                "dones": np.zeros((rollout_steps, n_envs, 1), dtype=np.float32),
            }
            next_values = rng.standard_normal((n_envs, 1), dtype=np.float32)
        else:  # shape/dtype templates only
            host_data = {
                "state": np.zeros((rollout_steps, n_envs, 5), dtype=np.float32),
                "actions": np.zeros((rollout_steps, n_envs, 4), dtype=np.float32),
                "logprobs": np.zeros((rollout_steps, n_envs, 1), dtype=np.float32),
                "values": np.zeros((rollout_steps, n_envs, 1), dtype=np.float32),
                "rewards": np.zeros((rollout_steps, n_envs, 1), dtype=np.float32),
                "dones": np.zeros((rollout_steps, n_envs, 1), dtype=np.float32),
            }
            next_values = np.zeros((n_envs, 1), dtype=np.float32)

        payload = transport.rollout_to_trainers(
            (host_data, next_values, np.asarray(jax.random.PRNGKey(round_i)), np.float32(0.2), np.float32(0.0))
        )
        device_data, dev_next_values, train_key, clip_coef, ent_coef = payload
        params, opt_state, _flat, _metrics = train_fn(
            params,
            opt_state,
            device_data,
            dev_next_values,
            train_key.astype(jnp.uint32),
            clip_coef,
            ent_coef,
            jnp.float32(1.0),  # lr_scale: no sentinel backoff in this drill
        )

    player_params = transport.params_to_player(params)

    params_after = np.concatenate(
        [np.asarray(leaf.addressable_data(0)).ravel() for leaf in jax.tree_util.tree_leaves(params)]
    )
    if transport.is_player_process:
        flat_player = np.concatenate(
            [np.asarray(leaf).ravel() for leaf in jax.tree_util.tree_leaves(player_params)]
        )
        player_matches = bool(np.array_equal(flat_player, params_after))
        player_device = str(jax.tree_util.tree_leaves(player_params)[0].devices())
    else:
        player_matches = player_params is None  # non-player processes hold no player copy
        player_device = None

    print(
        json.dumps(
            {
                "pid": pid,
                "changed": bool(np.abs(params_after - params_before).max() > 0),
                "digest": float(np.abs(params_after).sum()),
                "head": params_after[:5].round(6).tolist(),
                "player_matches": player_matches,
                "player_device": player_device,
            }
        )
    )
    # compile skew on a 1-core host can exceed the distributed shutdown-barrier
    # timeout; leave together
    runtime.barrier()


if __name__ == "__main__":
    main()
