"""Third-party algorithm packages register through the public decorator + search
path, without touching the sheeprl_tpu tree (reference
howto/register_external_algorithm.md + hydra_plugins search-path flow).
"""

import os
import sys
import textwrap

from sheeprl_tpu.cli import run
from sheeprl_tpu.utils.registry import algorithm_registry


def _write(path, content):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(content))


def test_external_algorithm_runs_through_cli(tmp_path, monkeypatch):
    pkg_root = tmp_path / "ext_project"
    marker = tmp_path / "ext_sota_ran.txt"

    _write(str(pkg_root / "my_awesome_algo" / "__init__.py"), "")
    _write(
        str(pkg_root / "my_awesome_algo" / "ext_sota.py"),
        f'''
        from sheeprl_tpu.utils.registry import register_algorithm


        @register_algorithm()
        def main(runtime, cfg):
            assert cfg.algo.name == "ext_sota"
            assert cfg.algo.sota_rate == 0.5  # external algo config reached the entrypoint
            with open({str(marker)!r}, "w") as f:
                f.write(f"world={{runtime.world_size}}")
        ''',
    )
    _write(
        str(pkg_root / "my_awesome_algo" / "utils.py"),
        """
        AGGREGATOR_KEYS = set()
        MODELS_TO_REGISTER = set()
        """,
    )
    _write(
        str(pkg_root / "my_awesome_configs" / "algo" / "ext_sota.yaml"),
        """
        defaults:
          - default
          - _self_
        name: ext_sota
        total_steps: 1000
        per_rank_batch_size: 8
        sota_rate: 0.5
        """,
    )
    _write(
        str(pkg_root / "my_awesome_configs" / "exp" / "ext_sota.yaml"),
        """
        # @package _global_
        defaults:
          - override /algo: ext_sota
          - override /env: dummy
          - _self_

        buffer:
          size: 64
        """,
    )

    monkeypatch.chdir(tmp_path)
    monkeypatch.syspath_prepend(str(pkg_root))
    monkeypatch.setenv("SHEEPRL_SEARCH_PATH", f"file://{pkg_root / 'my_awesome_configs'}")
    # the user's my_awesome_main.py imports the algo module before calling run()
    import importlib

    importlib.import_module("my_awesome_algo.ext_sota")
    assert any("my_awesome_algo" in m for m in algorithm_registry)

    try:
        run(
            overrides=[
                "exp=ext_sota",
                "env=dummy",
                "env.id=discrete_dummy",
                "fabric.accelerator=cpu",
                "fabric.devices=1",
                "dry_run=True",
                "metric.log_level=0",
                "checkpoint.save_last=False",
            ]
        )
    finally:
        # keep the registry clean for other tests in this process
        for mod in [m for m in list(algorithm_registry) if "my_awesome_algo" in m]:
            algorithm_registry.pop(mod, None)
        sys.modules.pop("my_awesome_algo.ext_sota", None)
        sys.modules.pop("my_awesome_algo.utils", None)
        sys.modules.pop("my_awesome_algo", None)

    assert marker.exists()
    assert marker.read_text() == "world=1"
