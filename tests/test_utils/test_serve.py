"""Unit tests for the policy-serving runtime (sheeprl_tpu/serve): micro-batcher
admission/backpressure/drain semantics, generation-swap atomicity under
concurrent load, and the hot-reloader's certified-sidecar edge cases (sidecar
appearing mid-scan, sidecar whose checkpoint was deleted, canary-failure
rollback). Everything here runs against fakes or tiny real checkpoints — the
full server + subprocess chaos drill lives in test_serve_smoke.py."""

import os
import threading
import time

import pytest

from sheeprl_tpu.serve import resolve
from sheeprl_tpu.serve.batcher import MicroBatcher
from sheeprl_tpu.serve.engine import Generation, GenerationStore
from sheeprl_tpu.serve.reload import HotReloader
from sheeprl_tpu.serve.stats import ServeStats


def _echo_compute(requests):
    return [{"echo": r.obs} for r in requests]


def _make_batcher(stats=None, **kw):
    defaults = dict(max_batch=4, max_wait_s=0.005, max_depth=8, stats=stats or ServeStats())
    defaults.update(kw)
    return MicroBatcher(_echo_compute, **defaults)


def _counter_sum(snap):
    return (
        snap["Serve/ok"]
        + snap["Serve/shed"]
        + snap["Serve/rejected"]
        + snap["Serve/deadline_missed"]
        + snap["Serve/errors"]
    )


# --------------------------------------------------------------------------- config
def test_resolve_fills_defaults_for_absent_group():
    # sidecar configs recorded before the serve subsystem existed still serve
    sv = resolve({})
    assert sv.batch.max_size == 16
    assert sv.queue.admission == "reject"
    assert sv.reload.enabled is True


def test_resolve_keeps_partial_overrides():
    sv = resolve({"serve": {"queue": {"admission": "shed_oldest"}}})
    assert sv.queue.admission == "shed_oldest"
    assert sv.queue.max_depth == 128  # sibling default still filled


# --------------------------------------------------------------------------- batcher
def test_batcher_serves_and_accounts():
    stats = ServeStats()
    b = _make_batcher(stats, max_depth=32).start()
    try:
        futs = [b.submit({"i": i}, rid=i) for i in range(10)]
        results = [f.result(timeout=5) for f in futs]
        assert all(r["status"] == "ok" for r in results)
        assert [r["id"] for r in results] == list(range(10))
    finally:
        b.close()
    snap = stats.snapshot()
    assert snap["Serve/requests_total"] == 10
    assert snap["Serve/ok"] == 10
    assert _counter_sum(snap) == snap["Serve/requests_total"]


def test_batcher_reject_admission_past_max_depth():
    stats = ServeStats()
    hold = threading.Event()

    def slow_compute(requests):
        hold.wait(5)
        return [{} for _ in requests]

    b = MicroBatcher(slow_compute, max_batch=1, max_wait_s=0.0, max_depth=2, stats=stats).start()
    try:
        futs = [b.submit({"i": i}, rid=i) for i in range(8)]
        # with compute blocked, at most 1 in flight + 2 queued are admitted
        rejected = [f.result(timeout=5) for f in futs if f.done() and f.result()["status"] == "rejected"]
        assert rejected, "expected rejections past max_depth"
        assert all(r["retry_after_ms"] > 0 for r in rejected)
        hold.set()
        statuses = {f.result(timeout=5)["status"] for f in futs}
        assert statuses == {"ok", "rejected"}
    finally:
        hold.set()
        b.close()
    snap = stats.snapshot()
    assert snap["Serve/rejected"] > 0
    assert _counter_sum(snap) == snap["Serve/requests_total"] == 8


def test_batcher_shed_oldest_admission():
    stats = ServeStats()
    hold = threading.Event()

    def slow_compute(requests):
        hold.wait(5)
        return [{} for _ in requests]

    b = MicroBatcher(
        slow_compute, max_batch=1, max_wait_s=0.0, max_depth=2, admission="shed_oldest", stats=stats
    ).start()
    try:
        futs = [b.submit({"i": i}, rid=i) for i in range(8)]
        shed = [f.result(timeout=1) for f in futs if f.done() and f.result()["status"] == "shed"]
        assert shed, "expected oldest-queued requests to be shed"
        # a shed carries the same back-off hint as a reject: a fleet router
        # (or any client) can schedule the retry instead of hammering
        assert all(r["retry_after_ms"] > 0 for r in shed)
        # freshest observations win: the shed ids are strictly older than the
        # ids still waiting in the queue
        hold.set()
        final = [f.result(timeout=5) for f in futs]
        ok_ids = [r["id"] for r in final if r["status"] == "ok"]
        shed_ids = [r["id"] for r in final if r["status"] == "shed"]
        assert max(shed_ids) < max(ok_ids)
    finally:
        hold.set()
        b.close()
    snap = stats.snapshot()
    assert snap["Serve/shed"] > 0
    assert _counter_sum(snap) == snap["Serve/requests_total"] == 8


def test_batcher_shed_oldest_prefers_lowest_priority_class():
    stats = ServeStats()
    hold = threading.Event()

    def slow_compute(requests):
        hold.wait(5)
        return [{} for _ in requests]

    b = MicroBatcher(
        slow_compute, max_batch=1, max_wait_s=0.0, max_depth=2, admission="shed_oldest", stats=stats
    ).start()
    try:
        f_busy = b.submit({"i": "busy"}, rid="busy", priority=1)
        deadline = time.monotonic() + 5
        while b._queue and time.monotonic() < deadline:
            time.sleep(0.005)
        assert not b._queue, "compute never picked up the in-flight request"
        f_p0 = b.submit({}, rid="p0-old", priority=0)
        f_p1a = b.submit({}, rid="p1-a", priority=1)
        # queue full at [p0-old, p1-a]: a priority-1 newcomer evicts the
        # best-effort request, NOT the oldest overall and NOT itself
        f_p1b = b.submit({}, rid="p1-b", priority=1)
        r = f_p0.result(timeout=5)
        assert r["status"] == "shed"
        assert r["retry_after_ms"] > 0
        assert not f_p1a.done() and not f_p1b.done()
        # queue full at [p1-a, p1-b]: a best-effort newcomer is strictly lower
        # priority than everything queued, so it sheds ITSELF
        f_p0b = b.submit({}, rid="p0-new", priority=0)
        r = f_p0b.result(timeout=5)
        assert r["status"] == "shed"
        assert r["retry_after_ms"] > 0
        hold.set()
        for f in (f_busy, f_p1a, f_p1b):
            assert f.result(timeout=5)["status"] == "ok"
    finally:
        hold.set()
        b.close()
    snap = stats.snapshot()
    assert snap["Serve/shed"] == 2
    assert _counter_sum(snap) == snap["Serve/requests_total"] == 5


def test_batcher_expired_deadline_dropped_before_compute():
    stats = ServeStats()
    computed = []

    def recording_compute(requests):
        computed.extend(r.rid for r in requests)
        return [{} for _ in requests]

    b = MicroBatcher(recording_compute, max_batch=4, max_wait_s=0.05, max_depth=8, stats=stats)
    fut_dead = b.submit({"x": 1}, deadline_s=0.001, rid="dead")
    fut_live = b.submit({"x": 2}, deadline_s=30.0, rid="live")
    time.sleep(0.02)  # let the deadline lapse BEFORE the worker starts
    b.start()
    try:
        assert fut_dead.result(timeout=5)["status"] == "deadline_expired"
        assert fut_live.result(timeout=5)["status"] == "ok"
        assert computed == ["live"]  # no compute spent on dead work
    finally:
        b.close()
    snap = stats.snapshot()
    assert snap["Serve/deadline_missed"] == 1
    assert _counter_sum(snap) == snap["Serve/requests_total"] == 2


def test_batcher_compute_failure_fails_batch_not_server():
    stats = ServeStats()

    def broken_compute(requests):
        raise RuntimeError("device wedged")

    b = MicroBatcher(broken_compute, max_batch=4, max_wait_s=0.005, max_depth=8, stats=stats).start()
    try:
        r = b.submit({"x": 1}, rid="a").result(timeout=5)
        assert r["status"] == "error"
        assert "device wedged" in r["error"]
        # the worker survived: a later batch still resolves
        r2 = b.submit({"x": 2}, rid="b").result(timeout=5)
        assert r2["status"] == "error"
    finally:
        b.close()
    snap = stats.snapshot()
    assert _counter_sum(snap) == snap["Serve/requests_total"] == 2


def test_batcher_drain_serves_admitted_rejects_new():
    stats = ServeStats()
    b = _make_batcher(stats).start()
    futs = [b.submit({"i": i}, rid=i) for i in range(4)]
    assert b.drain(timeout=5) is True
    late = b.submit({"i": 99}, rid=99).result(timeout=5)
    assert late["status"] == "rejected"
    assert late["reason"] == "draining"
    assert all(f.result(timeout=5)["status"] == "ok" for f in futs)
    b.close()
    snap = stats.snapshot()
    assert _counter_sum(snap) == snap["Serve/requests_total"] == 5


def test_batcher_pow2_occupancy_observed():
    stats = ServeStats()
    b = _make_batcher(stats, max_wait_s=0.05).start()
    try:
        futs = [b.submit({"i": i}, rid=i) for i in range(3)]
        [f.result(timeout=5) for f in futs]
    finally:
        b.close()
    snap = stats.snapshot()
    # 3 live requests pad onto the 4-bucket (or split across smaller buckets
    # if the worker woke early); occupancy is live/bucket in (0, 1]
    assert 0 < snap["Serve/batch_occupancy"] <= 1.0


# --------------------------------------------------------------------------- generations
def test_generation_store_swap_returns_previous():
    g1 = Generation(gen_id=1, params="p1", source="a")
    g2 = Generation(gen_id=2, params="p2", source="b")
    store = GenerationStore(g1)
    assert store.gen_id == 1
    prev = store.swap(g2)
    assert prev is g1
    assert store.get() is g2
    # rollback is just swapping the previous generation back
    store.swap(prev)
    assert store.gen_id == 1


def test_generation_swap_never_tears_inflight_batches():
    """A batch pins ONE generation for its whole lifetime: under a storm of
    concurrent swaps, every response's (params tag, gen_id) pair must be
    self-consistent — half-old/half-new reads would break the pairing."""
    store = GenerationStore(Generation(gen_id=1, params="tag-1", source="boot"))
    stop = threading.Event()

    def swapper():
        gid = 2
        while not stop.is_set():
            store.swap(Generation(gen_id=gid, params=f"tag-{gid}", source="swap"))
            gid += 1
            time.sleep(0.0005)

    def pinned_compute(requests):
        gen = store.get()  # ONE read pins the batch, exactly like PolicyServer._compute
        time.sleep(0.002)  # hold the batch open across many swap opportunities
        return [{"gen": gen.gen_id, "tag": gen.params} for _ in requests]

    b = MicroBatcher(pinned_compute, max_batch=4, max_wait_s=0.001, max_depth=512).start()
    t = threading.Thread(target=swapper, daemon=True)
    t.start()
    try:
        futs = [b.submit({"i": i}, rid=i) for i in range(200)]
        results = [f.result(timeout=30) for f in futs]
    finally:
        stop.set()
        t.join(timeout=5)
        b.close()
    assert all(r["status"] == "ok" for r in results)
    for r in results:
        assert r["tag"] == f"tag-{r['gen']}", f"torn generation read: {r}"
    assert len({r["gen"] for r in results}) > 1, "swaps never landed; the race was not exercised"


# --------------------------------------------------------------------------- reloader
class _FakeEngine:
    """Just enough engine surface for HotReloader: records calls, optionally
    fails warm-up or the canary."""

    def __init__(self, fail_warm=False, fail_canary=False):
        self.fail_warm = fail_warm
        self.fail_canary = fail_canary
        self.made = []
        self.canaried = []

    def make_generation(self, state, gen_id, source, info):
        info = info or {}
        gen = Generation(
            gen_id=gen_id,
            params=state["agent"],
            source=source,
            step=info.get("policy_step", info.get("step")),
            crc32=info.get("crc32"),
        )
        self.made.append(gen)
        return gen

    def warm_sync(self):
        if self.fail_warm:
            raise RuntimeError("warmup wedged")

    def canary(self, params):
        self.canaried.append(params)
        if self.fail_canary:
            raise RuntimeError("non-finite canary")


def _reloader(tmp_path, engine, store=None, **kw):
    store = store or GenerationStore(Generation(gen_id=1, params="boot", source="boot"))
    stats = ServeStats()
    r = HotReloader(engine, store, str(tmp_path), stats, poll_s=60.0, **kw)
    return r, store, stats


def _write_certified(tmp_path, step, payload=None):
    from sheeprl_tpu.utils.checkpoint import certify, save_state

    path = os.path.join(str(tmp_path), f"ckpt_{step}_0.ckpt")
    info = save_state(path, payload or {"agent": f"weights-{step}"})
    certify(path, crc32=info.get("crc32"), size=info.get("size"), policy_step=step)
    return path


def test_reloader_swaps_newly_certified_checkpoint(tmp_path):
    engine = _FakeEngine()
    r, store, stats = _reloader(tmp_path, engine)
    assert r.scan_once() is None  # empty dir: nothing to do
    _write_certified(tmp_path, 100)
    assert r.scan_once() == 2
    assert store.gen_id == 2
    assert store.get().step == 100  # policy_step from the sidecar rides along
    assert stats.snapshot()["Serve/reload_generations"] == 1
    # second scan of the SAME artifact is a no-op (identity = path + crc)
    assert r.scan_once() is None
    assert store.gen_id == 2


def test_reloader_ignores_uncertified_and_midscan_sidecars(tmp_path):
    """A sidecar appearing for a checkpoint that is half-written, deleted, or
    overwritten must read as not-certified and be skipped, not crashed on."""
    import json

    from sheeprl_tpu.utils.checkpoint import certified_sidecar

    engine = _FakeEngine()
    r, store, _ = _reloader(tmp_path, engine)
    # bare checkpoint without sidecar: invisible
    from sheeprl_tpu.utils.checkpoint import save_state

    bare = os.path.join(str(tmp_path), "ckpt_50_0.ckpt")
    save_state(bare, {"agent": "uncertified"})
    assert r.scan_once() is None
    # sidecar whose checkpoint bytes were OVERWRITTEN after certification
    # (mid-scan appearance): size/CRC mismatch -> skipped
    path = _write_certified(tmp_path, 60)
    with open(path, "wb") as f:
        f.write(b"torn" * 100)
    assert r.scan_once() is None
    assert store.gen_id == 1
    # sidecar whose checkpoint was DELETED: skipped, not crashed on
    path2 = _write_certified(tmp_path, 70)
    os.remove(path2)
    assert r.scan_once() is None
    assert store.gen_id == 1
    # a fabricated sidecar pointing at nothing at all
    ghost = certified_sidecar(os.path.join(str(tmp_path), "ckpt_80_0.ckpt"))
    with open(ghost, "w") as f:
        json.dump({"certified": True, "crc32": 1, "size": 1}, f)
    assert r.scan_once() is None
    assert store.gen_id == 1
    assert engine.made == []  # nothing was ever loaded


def test_reloader_warm_failure_keeps_current_generation(tmp_path):
    engine = _FakeEngine(fail_warm=True)
    r, store, stats = _reloader(tmp_path, engine, degraded_after=2)
    _write_certified(tmp_path, 100)
    assert r.scan_once() is None
    assert store.gen_id == 1  # no swap on a warm failure
    snap = stats.snapshot()
    assert snap["Serve/reload_failures"] == 1
    assert snap["Serve/degraded"] == 0.0  # below the latch threshold
    assert r.scan_once() is None  # same artifact retried (identity never recorded)
    assert stats.snapshot()["Serve/degraded"] == 1.0  # latched after 2 consecutive


def test_reloader_canary_failure_rolls_back(tmp_path):
    engine = _FakeEngine(fail_canary=True)
    r, store, stats = _reloader(tmp_path, engine)
    boot = store.get()
    _write_certified(tmp_path, 100)
    assert r.scan_once() is None
    assert store.get() is boot  # the previous generation is back
    snap = stats.snapshot()
    assert snap["Serve/reload_rollbacks"] == 1
    assert snap["Serve/reload_failures"] == 1
    assert snap["Serve/reload_generations"] == 0


def test_reloader_recovers_after_failures(tmp_path):
    engine = _FakeEngine(fail_canary=True)
    r, store, stats = _reloader(tmp_path, engine, degraded_after=1)
    _write_certified(tmp_path, 100)
    assert r.scan_once() is None
    assert stats.snapshot()["Serve/degraded"] == 1.0
    # the swap path un-wedges (e.g. the trainer certifies a healthy artifact)
    engine.fail_canary = False
    _write_certified(tmp_path, 200)
    assert r.scan_once() == 2
    snap = stats.snapshot()
    assert snap["Serve/degraded"] == 0.0  # cleared on success
    assert store.get().step == 200


def test_reloader_recovery_emits_incident_close_event(tmp_path):
    """The success that clears the degraded latch writes a
    ``serve_reload_recovered`` event row (with the failure streak it cleared);
    an ordinary healthy reload does not — recovery rows close incidents."""
    import json

    # keep the reloader's events dir (``dirname(ckpt_dir)/health``) inside
    # tmp_path by scanning a subdirectory
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    engine = _FakeEngine(fail_canary=True)
    r, store, stats = _reloader(ckpt_dir, engine, degraded_after=1)
    _write_certified(ckpt_dir, 100)
    assert r.scan_once() is None
    assert stats.snapshot()["Serve/degraded"] == 1.0
    engine.fail_canary = False
    _write_certified(ckpt_dir, 200)
    assert r.scan_once() == 2

    events_path = os.path.join(r.events_dir, "events.jsonl")
    rows = [json.loads(line) for line in open(events_path)]
    recovered = [e for e in rows if e["event"] == "serve_reload_recovered"]
    assert len(recovered) == 1
    assert recovered[0]["failures_cleared"] == 1
    assert recovered[0]["step"] == 200
    assert recovered[0]["gen_id"] == 2

    # a further healthy reload (no latch to clear) must NOT re-emit
    _write_certified(ckpt_dir, 300)
    assert r.scan_once() == 3
    rows = [json.loads(line) for line in open(events_path)]
    assert len([e for e in rows if e["event"] == "serve_reload_recovered"]) == 1


def test_reloader_skips_boot_artifact(tmp_path):
    """The generation the server booted from must not be re-loaded as gen 2:
    the boot sidecar's crc is stamped into the boot Generation."""
    from sheeprl_tpu.utils.checkpoint import certified_info

    path = _write_certified(tmp_path, 100)
    info = certified_info(path)
    store = GenerationStore(
        Generation(gen_id=1, params="boot", source=path, crc32=info["crc32"])
    )
    engine = _FakeEngine()
    r, store, _ = _reloader(tmp_path, engine, store=store)
    assert r.scan_once() is None
    assert store.gen_id == 1
    assert engine.made == []
