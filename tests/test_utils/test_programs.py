"""Compiled-program observatory (sheeprl_tpu/telemetry/programs.py): row
schema round-trip through a REAL AOT compile, HLO-fingerprint stability, the
diff CLI catching a seeded memory regression and a sharding change, the
warm-step zero-cost proof under ``jax.transfer_guard``, the Prometheus
collision dedupe, and the bench cross-run regression sentinel."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench
from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.core import failpoints
from sheeprl_tpu.telemetry import export as tel_export
from sheeprl_tpu.telemetry import programs as tel_programs
from sheeprl_tpu.telemetry import registry as tel_registry
from sheeprl_tpu.telemetry import trace

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _clean_observatory():
    trace.disable()
    tel_registry.clear()
    failpoints.reset()
    tel_programs.reset()
    yield
    trace.disable()
    tel_registry.clear()
    failpoints.reset()
    tel_programs.reset()


def _compile_demo(name="obs.demo", n=32, **jit_kwargs):
    gfn = jax_compile.guarded_jit(
        lambda x, y: (x @ y).sum(), name=name, donate_argnums=(0,), **jit_kwargs
    )
    spec = jax.ShapeDtypeStruct((n, n), jnp.float32)
    gfn.aot_compile(spec, spec)
    return gfn


# --------------------------------------------------------------------------- #
# capture: one real compile -> one complete, schema-versioned JSONL row
# --------------------------------------------------------------------------- #


def test_ledger_row_schema_roundtrip(tmp_path):
    path = str(tmp_path / "programs.jsonl")
    tel_programs.configure(path, mirror_env=False)
    trace.configure(plane="test", trace_id="progrows")
    _compile_demo()

    rows = tel_programs.read_ledger(path)
    assert len(rows) == 1
    row = rows[0]
    assert row["schema"] == tel_programs.SCHEMA_VERSION
    assert row["name"] == "obs.demo"
    # the acceptance bar: fingerprint, FLOPs, HBM breakdown and shardings all
    # non-null for a program compiled on this (CPU) backend
    assert isinstance(row["fingerprint"], str) and len(row["fingerprint"]) == 24
    assert row["flops"] > 0
    assert row["compile_seconds"] > 0
    mem = row["memory"]
    for key in (
        "argument_bytes",
        "output_bytes",
        "temp_bytes",
        "generated_code_bytes",
        "alias_bytes",
        "peak_bytes",
    ):
        assert key in mem, f"memory breakdown missing {key}"
    assert row["input_shardings"] and row["output_shardings"]
    assert row["donation"] == {"argnums": [0]}
    assert row["trace_id"] == "progrows"
    assert row["backend"] == "cpu"
    json.dumps(row)  # the ledger contract: plain-JSON rows

    # the in-memory registry feeds the metrics fabric even without a path
    g = tel_programs.gauges()
    assert g["Programs/recorded"] == 1.0
    assert g["Program/obs.demo/peak_hbm_bytes"] == mem["peak_bytes"]
    assert g["Program/obs.demo/flops"] == row["flops"]


def test_fingerprint_stable_across_recompiles_and_churns_on_change():
    def f(x, y):
        return (x @ y).sum()

    spec = jax.ShapeDtypeStruct((16, 16), jnp.float32)
    fps = []
    for _ in range(2):
        tel_programs.reset()
        jax_compile.guarded_jit(f, name="obs.fp").aot_compile(spec, spec)
        fps.append(tel_programs.snapshot()[0]["fingerprint"])
    assert fps[0] == fps[1], "identical program must hash identically across compiles"

    tel_programs.reset()
    wide = jax.ShapeDtypeStruct((32, 16), jnp.float32)
    jax_compile.guarded_jit(f, name="obs.fp").aot_compile(wide, spec)
    assert tel_programs.snapshot()[0]["fingerprint"] != fps[0], "shape change must churn the hash"


def test_mesh_sharded_program_records_named_shardings():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    # conftest forces 8 host-platform devices; a 2-device mesh is always there
    mesh = Mesh(np.array(jax.devices()[:2]), ("d",))
    sharded = NamedSharding(mesh, PartitionSpec("d"))
    spec = jax.ShapeDtypeStruct((16, 8), jnp.float32)

    jax_compile.guarded_jit(lambda x: x * 2.0, name="obs.mesh.repl").aot_compile(spec)
    jax_compile.guarded_jit(
        lambda x: x * 2.0, name="obs.mesh.shard", in_shardings=(sharded,), out_shardings=sharded
    ).aot_compile(spec)

    rows = {r["name"]: r for r in tel_programs.snapshot()}
    sh = rows["obs.mesh.shard"]["input_shardings"]
    assert sh and any("NamedSharding" in s for s in sh)
    assert sh != rows["obs.mesh.repl"]["input_shardings"]
    assert rows["obs.mesh.shard"]["num_devices"] >= 2


def test_record_failpoint_reaches_the_chaos_drill_and_only_it():
    failpoints.configure("telemetry.program_record:raise")
    with pytest.raises(failpoints.FailpointError):
        _compile_demo(name="obs.drill")
    failpoints.reset()
    # any OTHER capture failure degrades to a skipped row, never a failed compile
    _compile_demo(name="obs.ok")
    assert tel_programs.stats()["rows_recorded"] == 1


def test_warm_step_never_touches_the_observatory(monkeypatch):
    """Recording happens at compile time ONLY: a warm call does zero ledger
    work and zero host transfers (the steady-state cost of the observatory)."""
    gfn = jax_compile.guarded_jit(lambda x: x + 1.0, name="obs.warm")
    spec = jax.ShapeDtypeStruct((8,), jnp.float32)
    gfn.aot_compile(spec)
    x = jax.device_put(jnp.zeros((8,), jnp.float32))
    x = gfn(x)  # first dispatch through the AOT executable
    jax.block_until_ready(x)
    assert tel_programs.stats()["rows_recorded"] == 1

    def boom(*a, **k):
        raise AssertionError("programs.record() reached from a warm step")

    monkeypatch.setattr(tel_programs, "record", boom)
    with jax.transfer_guard("disallow"):
        x = gfn(x)
        jax.block_until_ready(x)  # fence only — not a transfer
    assert tel_programs.stats()["rows_recorded"] == 1


def test_env_var_wins_over_train_loop_default(tmp_path, monkeypatch):
    pinned = str(tmp_path / "parent.jsonl")
    monkeypatch.setenv(tel_programs.ENV_VAR, pinned)
    tel_programs.configure_from_env()
    # the per-run default a train loop installs must not sever the parent pin
    tel_programs.configure_default(str(tmp_path / "child.jsonl"))
    assert tel_programs.ledger_path() == pinned


# --------------------------------------------------------------------------- #
# diff CLI: seeded +10% temp-HBM and a sharding flip must be flagged (rc 1)
# --------------------------------------------------------------------------- #


def _doctored_copy(rows, *, temp_factor=1.10, flip_sharding=True):
    out = []
    for row in rows:
        row = json.loads(json.dumps(row))  # deep copy
        mem = row.get("memory") or {}
        if "temp_bytes" in mem:
            delta = mem["temp_bytes"] * (temp_factor - 1.0) or 4096.0 * (temp_factor - 1.0) * 10
            mem["temp_bytes"] += delta
            mem["peak_bytes"] = mem.get("peak_bytes", 0.0) + delta
        if flip_sharding and row.get("input_shardings"):
            row["input_shardings"] = ["NamedSharding(resharded)"] + row["input_shardings"][1:]
        out.append(row)
    return out


def test_diff_cli_flags_seeded_memory_and_sharding_regressions(tmp_path, capsys):
    ledger_a = str(tmp_path / "a" / "programs.jsonl")
    tel_programs.configure(ledger_a, mirror_env=False)
    _compile_demo(name="obs.diff", n=64)
    rows = tel_programs.read_ledger(ledger_a)
    assert rows and rows[0]["memory"]["temp_bytes"] >= 0

    ledger_b = str(tmp_path / "b" / "programs.jsonl")
    os.makedirs(os.path.dirname(ledger_b))
    with open(ledger_b, "w") as f:
        for row in _doctored_copy(rows):
            f.write(json.dumps(row) + "\n")

    rc = tel_programs.main(["diff", ledger_a, ledger_b, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(
        d["field"] == "temp_bytes" and d["regression"] for d in report["memory_deltas"]
    ) or any(d["field"] == "peak_bytes" and d["regression"] for d in report["memory_deltas"])
    assert any(c["io"] == "input_shardings" for c in report["sharding_changes"])
    assert report["regressions"]

    # identical ledgers: rc 0 and an explicitly clean text report
    rc = tel_programs.main(["diff", ledger_a, ledger_a])
    out = capsys.readouterr().out
    assert rc == 0 and "no regressions flagged" in out


def test_diff_resolves_run_directories_and_skips_torn_rows(tmp_path, capsys):
    run = tmp_path / "run" / "telemetry"
    run.mkdir(parents=True)
    row = {"schema": 1, "name": "p", "fingerprint": "x", "memory": {"temp_bytes": 10.0}}
    (run / "programs.jsonl").write_text(
        json.dumps(row) + "\n" + "{torn json\n" + json.dumps({**row, "schema": 99}) + "\n"
    )
    rows = tel_programs.read_ledger(str(run / "programs.jsonl"))
    assert len(rows) == 1, "corrupt and future-schema rows must be skipped"
    rc = tel_programs.main(["diff", str(tmp_path / "run"), str(tmp_path / "run")])
    capsys.readouterr()
    assert rc == 0


# --------------------------------------------------------------------------- #
# satellite: Prometheus name-collision dedupe in the exporter
# --------------------------------------------------------------------------- #


def test_prometheus_collision_dedupe_is_deterministic_and_counted():
    # "Programs/recorded" and "Programs.recorded" both sanitize to
    # sheeprl_programs_recorded — invalid exposition if both are emitted
    metrics = {"Programs/recorded": 1.0, "Programs.recorded": 2.0, "Other/ok": 3.0}
    text = tel_export.to_prometheus(metrics)
    body = [ln for ln in text.splitlines() if ln.startswith("sheeprl_programs_recorded")]
    assert body == ["sheeprl_programs_recorded 2"], body  # sorted order: '.' < '/'
    assert "sheeprl_export_series_dropped 1" in text
    assert "sheeprl_other_ok 3" in text
    # no collision -> no dropped series at all
    assert "export_series_dropped" not in tel_export.to_prometheus({"Other/ok": 3.0})


def test_registry_default_providers_include_programs():
    tel_registry.register_default_providers()
    _compile_demo(name="obs.fabric")
    merged = tel_registry.collect()
    assert merged.get("Programs/recorded") == 1.0
    assert "Program/obs.fabric/flops" in merged


# --------------------------------------------------------------------------- #
# satellite: fused-vs-split FLOP/MFU parity on the CartPole config
# --------------------------------------------------------------------------- #


def test_fused_and_split_flops_parity_on_cartpole(monkeypatch):
    """The fused whole-iteration program must account for the same work as
    collect + train compiled apart (cost_analysis FLOPs within tolerance —
    fusion changes scheduling, not the model math), and both paths' MFU
    numerators (``last_step_flops``) must equal their ledger rows."""
    import gymnasium as gym

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import make_train_fn, make_update_impl
    from sheeprl_tpu.config import instantiate, load_config
    from sheeprl_tpu.core.runtime import build_runtime
    from sheeprl_tpu.envs import ingraph as ig
    from sheeprl_tpu.telemetry import device as tel_device
    from sheeprl_tpu.utils.optim import with_clipping
    from sheeprl_tpu.utils.utils import PlayerParamsSync

    n_envs, t_steps = 16, 8
    n_data = n_envs * t_steps
    cfg = load_config(
        overrides=[
            "exp=ppo",
            "env=jax_cartpole",
            f"env.num_envs={n_envs}",
            f"algo.rollout_steps={t_steps}",
            f"algo.per_rank_batch_size={n_data}",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "seed=7",
        ]
    )
    runtime = build_runtime(cfg.fabric)
    venv = ig.make_vector_env(cfg, n_envs, 7, device=runtime.device)
    space = venv.single_action_space
    assert isinstance(space, gym.spaces.Discrete)
    agent, params, player = build_agent(
        runtime, (int(space.n),), False, cfg, venv.single_observation_space, None
    )
    player.params = jax.device_put(player.params, runtime.device)
    venv.reset(seed=7)
    tx = with_clipping(instantiate(dict(cfg.algo.optimizer))(), cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    params_sync = PlayerParamsSync(player.params)
    scalar = jax.ShapeDtypeStruct((), jnp.float32)

    # split path: rollout and train compiled apart
    split_col = ig.InGraphRolloutCollector(
        venv, player, rollout_steps=t_steps, gamma=float(cfg.algo.gamma), name="parity_split"
    )
    split_col.collect_fn.aot_compile(*split_col.warmup_specs())
    data_s, nv_s = split_col.output_specs()
    train_fn = make_train_fn(agent, tx, cfg, runtime, n_data, ["state"], [], params_sync)
    train_fn.aot_compile(
        jax_compile.specs_of(params),
        jax_compile.specs_of(opt_state),
        data_s,
        nv_s,
        jax_compile.spec_like(jax.random.PRNGKey(0)),
        scalar,
        scalar,
        scalar,
    )

    # fused path: its own collector instance (a shared one would leak tracers)
    fused_col = ig.InGraphRolloutCollector(
        venv, player, rollout_steps=t_steps, gamma=float(cfg.algo.gamma), name="parity_fused"
    )
    update_impl = make_update_impl(agent, tx, cfg, runtime, n_data, ["state"], [], params_sync)
    trainer = ig.FusedInGraphTrainer(fused_col, update_impl, n_extras=3, name="parity_fused")
    extras = (jnp.float32(cfg.algo.clip_coef), jnp.float32(cfg.algo.ent_coef), jnp.float32(1.0))
    trainer.step_fn.aot_compile(
        *trainer.warmup_specs(params, opt_state, jax.random.PRNGKey(5), *extras)
    )

    rows = {r["name"]: r for r in tel_programs.snapshot()}
    fused = rows["parity_fused.ingraph_train"]["flops"]
    split = rows["parity_split.ingraph_collect"]["flops"] + rows["ppo.train"]["flops"]
    assert fused > 0 and split > 0
    assert abs(fused - split) / split < 0.25, (fused, split)

    # the MFU numerators are exactly the ledger FLOPs on both paths
    assert trainer.step_fn.last_step_flops == fused
    assert train_fn.last_step_flops == rows["ppo.train"]["flops"]

    # identical FLOPs + time => identical MFU math on both paths (CPU has no
    # peak-FLOPs table entry, so pin one)
    monkeypatch.setattr(tel_device, "chip_peak_flops", lambda device=None: 1.0e12)
    assert tel_device.mfu(fused, 0.01, runtime.device) == pytest.approx(fused / 0.01 / 1.0e12)
    assert tel_device.mfu(rows["ppo.train"]["flops"], 0.01, runtime.device) == pytest.approx(
        rows["ppo.train"]["flops"] / 0.01 / 1.0e12
    )
    venv.close()


# --------------------------------------------------------------------------- #
# bench cross-run regression sentinel
# --------------------------------------------------------------------------- #


def _write_bench_ledger(path, rows):
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


_BASE_ROUND = {
    "status": "ok",
    "env_steps_per_sec": 1000.0,
    "infer_p99_ms": 10.0,
    "device_hbm_peak_bytes": 1.0e9,
    "mfu": 0.30,
}


def test_sentinel_passes_on_a_clean_ledger(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    _write_bench_ledger(path, [dict(_BASE_ROUND, run_id=f"r{i}") for i in range(4)])
    report, rc = bench.check_regressions(path)
    assert rc == 0 and report["status"] == "ok"
    assert report["checked"] >= 3
    assert report["Regress/env_steps_per_sec"]["breach"] is False


def test_sentinel_fails_on_a_doctored_round(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    rows = [dict(_BASE_ROUND, run_id=f"r{i}") for i in range(3)]
    rows.append(
        dict(_BASE_ROUND, run_id="bad", env_steps_per_sec=500.0, infer_p99_ms=40.0)
    )
    report, rc = bench.check_regressions(path)
    # ledger not written yet: missing file is a skip, not a crash
    assert rc == 0 and report["status"] == "skipped"
    _write_bench_ledger(path, rows)
    report, rc = bench.check_regressions(path)
    assert rc == 4 and report["status"] == "regressed"
    assert "env_steps_per_sec" in report["regressions"]
    assert "infer_p99_ms" in report["regressions"]
    assert report["Regress/env_steps_per_sec"]["direction"] == "higher"
    assert report["Regress/device_hbm_peak_bytes"]["breach"] is False

    # per-metric threshold override: a 50%-drop allowance silences the SPS breach
    report, rc = bench.check_regressions(path, {"env_steps_per_sec": 0.6, "infer_p99_ms": 5.0})
    assert rc == 0, report["regressions"]


def test_sentinel_compares_only_same_status_rounds(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    rows = [dict(_BASE_ROUND, run_id="cpu0", status="cpu_fallback", env_steps_per_sec=50.0)]
    rows.append(dict(_BASE_ROUND, run_id="ok0"))
    _write_bench_ledger(path, rows)
    report, rc = bench.check_regressions(path)
    # an ok round must never be judged against cpu_fallback history
    assert rc == 0 and report["status"] == "skipped"


def test_bench_ledger_append_roundtrip_and_failpoint_drop(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    bench._append_ledger({"status": "ok", "value": 1}, path)
    failpoints.configure("bench.ledger_append:drop")
    bench._append_ledger({"status": "ok", "value": 2}, path)
    failpoints.reset()
    rows = bench._read_bench_ledger(path)
    assert [r["value"] for r in rows] == [1], "dropped append must not reach the file"


def test_parse_thresholds():
    assert bench._parse_thresholds(["a=0.5", "b_p99_ms=1.0"]) == {"a": 0.5, "b_p99_ms": 1.0}
    with pytest.raises(SystemExit):
        bench._parse_thresholds(["nope"])
