"""Versioned checkpoint container: integrity + manifest + version gating.

Reference parity is plain ``torch.save`` pickles; the TPU build adds a format
version, a leaf manifest, and a CRC so resume fails loudly on corrupt or
inconsistent checkpoints instead of silently training from garbage.
"""

import pickle

import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.utils.checkpoint import (
    CKPT_FORMAT_VERSION,
    load_state,
    read_manifest,
    save_state,
)


def _state():
    return {
        "agent": {"dense": {"kernel": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)}},
        "iter_num": 7,
        "rewards": np.ones((4, 1), np.float32),
    }


def test_roundtrip_and_manifest(tmp_path):
    path = str(tmp_path / "ckpt.ckpt")
    save_state(path, _state())
    state = load_state(path)
    np.testing.assert_array_equal(
        np.asarray(state["agent"]["dense"]["kernel"]), np.arange(6, dtype=np.float32).reshape(2, 3)
    )
    assert state["iter_num"] == 7
    manifest = read_manifest(path)
    assert manifest is not None
    assert any("kernel" in k for k in manifest)
    kern_key = next(k for k in manifest if "kernel" in k)
    assert manifest[kern_key] == ((2, 3), "float32")


def test_corrupt_payload_raises(tmp_path):
    path = str(tmp_path / "ckpt.ckpt")
    save_state(path, _state())
    raw = bytearray(open(path, "rb").read())
    # flip a byte well inside the embedded state payload
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    with pytest.raises((RuntimeError, pickle.UnpicklingError), match="integrity|corrupt|unreadable|pickle"):
        load_state(path)


def test_truncated_file_raises(tmp_path):
    path = str(tmp_path / "ckpt.ckpt")
    save_state(path, _state())
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) // 3])
    with pytest.raises(RuntimeError, match="unreadable|truncated"):
        load_state(path)


def test_future_format_version_raises(tmp_path):
    path = str(tmp_path / "ckpt.ckpt")
    with open(path, "wb") as f:
        pickle.dump(
            {"__format__": "sheeprl_tpu_ckpt", "format_version": CKPT_FORMAT_VERSION + 1, "manifest": {}},
            f,
        )
        pickle.dump({"x": 1}, f)
        pickle.dump({"crc32": 0}, f)
    with pytest.raises(RuntimeError, match="format_version"):
        load_state(path)


def test_manifest_mismatch_raises(tmp_path):
    import zlib

    path = str(tmp_path / "ckpt.ckpt")
    payload = pickle.dumps({"agent": np.zeros((2, 2), np.float32)}, protocol=pickle.HIGHEST_PROTOCOL)
    with open(path, "wb") as f:
        pickle.dump(
            {
                "__format__": "sheeprl_tpu_ckpt",
                "format_version": CKPT_FORMAT_VERSION,
                # manifest claims a different shape than the state actually holds
                "manifest": {"['agent']": ((4, 4), "float32")},
            },
            f,
        )
        f.write(payload)
        pickle.dump({"crc32": zlib.crc32(payload)}, f)
    with pytest.raises(RuntimeError, match="manifest"):
        load_state(path)


def test_legacy_bare_pickle_still_loads(tmp_path):
    path = str(tmp_path / "legacy.ckpt")
    with open(path, "wb") as f:
        pickle.dump({"iter_num": 3, "agent": np.ones((2,), np.float32)}, f)
    state = load_state(path)
    assert state["iter_num"] == 3


def test_read_manifest_never_unpickles_legacy_payload(tmp_path, monkeypatch):
    """Legacy bare pickles are recognized from a header sniff; the (potentially
    multi-GB) state pickle must not be loaded just to return None (advisor r4)."""
    import pickle

    import numpy as np

    from sheeprl_tpu.utils import checkpoint as ckpt_mod

    legacy = tmp_path / "legacy.ckpt"
    with open(legacy, "wb") as f:
        pickle.dump({"agent": np.zeros((8, 8))}, f)

    def boom(*a, **k):  # any unpickle of the legacy file is the regression
        raise AssertionError("read_manifest unpickled a legacy checkpoint payload")

    monkeypatch.setattr(ckpt_mod.pickle, "load", boom)
    assert ckpt_mod.read_manifest(str(legacy)) is None

    # v1 container: only the header pickle is read (small), manifest returned
    monkeypatch.undo()
    v1 = tmp_path / "v1.ckpt"
    ckpt_mod.save_state(str(v1), {"agent": np.ones((2, 2))})
    manifest = ckpt_mod.read_manifest(str(v1))
    assert manifest is not None and any("agent" in k for k in manifest)


def test_read_manifest_rejects_legacy_with_embedded_magic(tmp_path, monkeypatch):
    """The v1 sniff checks the opcode structure at the header's FIXED offsets,
    not 'magic substring anywhere in the first 256 bytes': a legacy state dict
    whose first key merely CONTAINS the magic (so the magic bytes sit in the
    head) must still be classified legacy -> None, without unpickling."""
    from sheeprl_tpu.utils import checkpoint as ckpt_mod

    for name, legacy_state in [
        # magic bytes land in the head via the first dict key
        ("keyed.ckpt", {"sheeprl_tpu_ckpt_dir": "/x", "agent": np.zeros((4,), np.float32)}),
        # exact magic as the first key, but NOT under a "__format__" key
        ("exact.ckpt", {"sheeprl_tpu_ckpt": 1, "agent": np.zeros((4,), np.float32)}),
        # "__format__" present with the WRONG magic value
        ("wrongmagic.ckpt", {"__format__": "someone_elses_ckpt", "agent": 1}),
    ]:
        path = tmp_path / name
        with open(path, "wb") as f:
            pickle.dump(legacy_state, f, protocol=pickle.HIGHEST_PROTOCOL)
        assert b"sheeprl_tpu_ckpt" in open(path, "rb").read(256) or name == "wrongmagic.ckpt"

        def boom(*a, **k):
            raise AssertionError(f"read_manifest unpickled legacy file {name}")

        with monkeypatch.context() as m:
            m.setattr(ckpt_mod.pickle, "load", boom)
            assert ckpt_mod.read_manifest(str(path)) is None


def test_read_manifest_v1_header_across_pickle_protocols(tmp_path):
    """The fixed-offset walk must accept the header layout of every protocol a
    writer could plausibly use (2/3: no FRAME, BINPUT memo, BINUNICODE strings;
    4/5: FRAME, MEMOIZE, SHORT_BINUNICODE)."""
    import zlib

    from sheeprl_tpu.utils.checkpoint import CKPT_FORMAT_VERSION as V

    manifest = {"['agent']": ((2, 2), "float32")}
    payload = pickle.dumps({"agent": np.zeros((2, 2), np.float32)}, protocol=pickle.HIGHEST_PROTOCOL)
    for proto in range(2, pickle.HIGHEST_PROTOCOL + 1):
        path = tmp_path / f"proto{proto}.ckpt"
        with open(path, "wb") as f:
            pickle.dump(
                {"__format__": "sheeprl_tpu_ckpt", "format_version": V, "manifest": manifest},
                f,
                protocol=proto,
            )
            f.write(payload)
            pickle.dump({"crc32": zlib.crc32(payload)}, f, protocol=proto)
        assert read_manifest(str(path)) == manifest, f"v1 header missed at protocol {proto}"
