"""Satellite registration of scripts/serve_smoke.py as a tier-1 test: the
policy-serving chaos drill — sustained client load over the TCP frontend must
survive a certified hot-reload to a second weight generation and a SIGTERM
kill/restart, with every request id resolving to exactly one terminal status,
the server-side counters summing exactly to requests_total at both shutdowns,
and zero steady-state retraces (full harness, fresh interpreters)."""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.faults
@pytest.mark.timeout(300)
def test_serve_smoke_chaos_drill(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "serve_smoke.py"),
            "--workdir",
            str(tmp_path),
            "--timeout",
            "240",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=280,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-2500:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "serve smoke OK" in out.stdout
    # the drill's own assertions already ran; independently re-audit the two
    # shutdown stats snapshots it leaves behind
    for name in ("stats1.json", "stats2.json"):
        with open(tmp_path / name) as f:
            stats = json.load(f)
        assert stats["drained"] is True, (name, stats)
        terminal = (
            stats["Serve/ok"]
            + stats["Serve/shed"]
            + stats["Serve/rejected"]
            + stats["Serve/deadline_missed"]
            + stats["Serve/errors"]
        )
        assert stats["Serve/requests_total"] == terminal, (name, stats)
        assert stats["Compile/retraces"] == 0, (name, stats)
        assert stats["Serve/ok"] > 0, (name, stats)
    # server B booted from the gen-1 checkpoint and must have hot-reloaded the
    # certified step-200 artifact
    with open(tmp_path / "stats2.json") as f:
        stats2 = json.load(f)
    assert stats2["Serve/reload_generations"] >= 1, stats2
    assert stats2["Serve/generation"] >= 2, stats2
