"""sheeprl_tpu/telemetry: tracer ring semantics, the zero-cost-when-disabled
guarantee, Chrome-trace schema, trace-id propagation into the health/failpoint/
checkpoint surfaces, the metrics fabric, and the no-host-traffic proof for
span recording around a warm fused iteration."""

import json
import os
import threading
import time

import pytest

from sheeprl_tpu.core import failpoints
from sheeprl_tpu.telemetry import device as tel_device
from sheeprl_tpu.telemetry import export as tel_export
from sheeprl_tpu.telemetry import registry as tel_registry
from sheeprl_tpu.telemetry import trace

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _clean_telemetry():
    trace.disable()
    tel_registry.clear()
    failpoints.reset()
    yield
    trace.disable()
    tel_registry.clear()
    failpoints.reset()


# --------------------------------------------------------------------------- #
# the production guarantee: disabled means ONE None-check, nothing else
# --------------------------------------------------------------------------- #


def test_disabled_tracing_never_reaches_the_recording_layer(monkeypatch):
    def boom(*a, **k):  # any recording work while disabled is a perf regression
        raise AssertionError("instrumentation reached past the `_tracer is None` guard")

    monkeypatch.setattr(trace, "_begin", boom)
    monkeypatch.setattr(trace, "_record_instant", boom)
    monkeypatch.setattr(trace, "_record_span", boom)
    assert trace.span("train/update", iter=1) is trace._NOOP
    assert trace.instant("whatever", x=1) is None
    assert trace.add_span("serve/request", 0.0, 1.0, status="ok") is None
    assert trace.new_span_id() == ""
    assert trace.current_trace_id() == ""
    assert trace.current_span_id() == ""
    assert not trace.enabled()


def test_disabled_span_is_a_shared_singleton():
    a = trace.span("x")
    b = trace.span("y", plane="serve", anything=3)
    assert a is b is trace._NOOP  # no allocation on the disabled path
    with a as sp:  # and it supports the full live-span surface
        assert sp.set(k=1) is sp
        assert sp.span_id == "" and sp.trace_id == ""
    assert trace.stats() == {"Telemetry/enabled": 0}
    assert trace.export() is None


# --------------------------------------------------------------------------- #
# ring semantics
# --------------------------------------------------------------------------- #


def test_ring_wraparound_keeps_newest_and_counts_drops():
    t = trace.configure(plane="train", capacity=4, trace_id="ringtest")
    for i in range(10):
        trace.instant(f"ev{i}")
    assert [ev[trace._EV_NAME] for ev in t.events()] == ["ev6", "ev7", "ev8", "ev9"]
    s = t.stats()
    assert s["Telemetry/spans_recorded"] == 10
    assert s["Telemetry/spans_dropped"] == 6
    assert s["Telemetry/ring_size"] == 4
    assert s["Telemetry/ring_capacity"] == 4


def test_span_nesting_records_parent_ids():
    t = trace.configure(plane="train", trace_id="nesttest")
    with trace.span("outer") as outer:
        assert trace.current_span_id() == outer.span_id
        with trace.span("inner") as inner:
            assert inner.span_id != outer.span_id
    evs = {ev[trace._EV_NAME]: ev for ev in t.events()}
    assert evs["inner"][trace._EV_PARENT] == outer.span_id
    assert evs["outer"][trace._EV_PARENT] == ""
    assert evs["outer"][trace._EV_DUR] >= evs["inner"][trace._EV_DUR]


def test_add_span_cross_thread_parenting_with_preallocated_id():
    """The serve request-lifecycle shape: the parent id is allocated at admit,
    the queue-wait child records (from another thread) BEFORE the parent."""
    t = trace.configure(plane="serve", trace_id="xthread")
    parent_id = trace.new_span_id()
    t0 = time.monotonic()
    done = threading.Event()

    def batcher_thread():
        trace.add_span("serve/queue_wait", t0, t0 + 0.01, parent_id=parent_id)
        done.set()

    threading.Thread(target=batcher_thread).start()
    assert done.wait(5.0)
    trace.add_span("serve/request", t0, t0 + 0.02, span_id=parent_id, status="ok")
    evs = {ev[trace._EV_NAME]: ev for ev in t.events()}
    assert evs["serve/queue_wait"][trace._EV_PARENT] == parent_id
    assert evs["serve/request"][trace._EV_SID] == parent_id
    assert evs["serve/request"][trace._EV_ARGS] == {"status": "ok"}


def test_span_records_exception_and_still_propagates():
    t = trace.configure(trace_id="exctest")
    with pytest.raises(ValueError, match="boom"):
        with trace.span("train/update"):
            raise ValueError("boom")
    (ev,) = t.events()
    assert ev[trace._EV_ARGS]["error"] == "ValueError: boom"


# --------------------------------------------------------------------------- #
# Chrome-trace / Perfetto schema
# --------------------------------------------------------------------------- #


def test_chrome_trace_schema(tmp_path):
    trace.configure(plane="serve", trace_id="cafe0123", capacity=64)
    with trace.span("serve/infer", batch=3):
        trace.instant("failpoint/reload.canary", action="raise")
    path = trace.export(str(tmp_path / "telemetry" / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    assert set(doc) == {"traceEvents", "displayTimeUnit", "metadata"}
    assert doc["displayTimeUnit"] == "ms"
    assert doc["metadata"]["trace_id"] == "cafe0123"
    assert doc["metadata"]["plane"] == "serve"
    meta, *events = doc["traceEvents"]
    assert meta["ph"] == "M" and meta["name"] == "process_name"
    assert meta["args"]["name"] == "sheeprl-serve"
    by_name = {e["name"]: e for e in events}
    x = by_name["serve/infer"]
    assert x["ph"] == "X" and x["cat"] == "serve"
    assert isinstance(x["ts"], float) and isinstance(x["dur"], float) and x["dur"] >= 0
    # wall-anchored microseconds: the ts must be ~now, not a raw perf_counter
    assert abs(x["ts"] / 1e6 - time.time()) < 300
    assert x["args"]["trace_id"] == "cafe0123" and x["args"]["batch"] == 3
    i = by_name["failpoint/reload.canary"]
    assert i["ph"] == "i" and i["s"] == "t" and i["args"]["action"] == "raise"
    # the instant nests under the enclosing span
    assert i["args"]["parent_id"] == x["args"]["span_id"]


def test_configure_mirrors_env_and_children_join_the_parents_trace():
    t = trace.configure(plane="orchestrate", capacity=32, trace_id="abcd1234")
    spec = os.environ[trace.ENV_VAR]
    assert "plane=orchestrate" in spec and "trace_id=abcd1234" in spec
    # what a spawned child would do at import time
    child = trace.configure_from_env({trace.ENV_VAR: spec})
    assert child.trace_id == t.trace_id == "abcd1234"
    assert child.plane == "orchestrate" and child.capacity == 32
    trace.disable()
    assert trace.ENV_VAR not in os.environ
    assert trace.configure_from_env({}) is None
    assert trace.configure_from_env({trace.ENV_VAR: "1"}).plane == "train"


# --------------------------------------------------------------------------- #
# trace-id propagation into the run's other record surfaces
# --------------------------------------------------------------------------- #


def test_trace_id_stamped_into_health_events(tmp_path):
    from sheeprl_tpu.core.health import append_event

    trace.configure(trace_id="deadbeef")
    append_event(str(tmp_path), "serve_reload_rollback", 7, path="x.ckpt")
    trace.disable()
    append_event(str(tmp_path), "divergence_detected", 9)
    rows = [json.loads(ln) for ln in (tmp_path / "events.jsonl").read_text().splitlines()]
    assert rows[0]["event"] == "serve_reload_rollback" and rows[0]["step"] == 7
    assert rows[0]["trace_id"] == "deadbeef" and rows[0]["path"] == "x.ckpt"
    assert "trace_id" not in rows[1]  # disabled: no empty-string noise


def test_trace_id_stamped_into_failpoint_hits_and_instants():
    trace.configure(trace_id="feedface")
    failpoints.configure("p:fire")
    assert failpoints.failpoint("p") is True
    assert failpoints.counts()["p"] == {"hits": 1, "fires": 1, "last_trace_id": "feedface"}
    names = [ev[trace._EV_NAME] for ev in trace.get_tracer().events()]
    assert "failpoint/p" in names


def test_trace_id_stamped_into_certified_sidecars(tmp_path):
    from sheeprl_tpu.utils.checkpoint import certified_sidecar, certify

    ckpt = str(tmp_path / "ckpt_10.safetensors")
    trace.configure(trace_id="0ddball0")
    certify(ckpt, crc32=123, size=456, policy_step=10)
    with open(certified_sidecar(ckpt)) as f:
        payload = json.load(f)
    assert payload["trace_id"] == "0ddball0" and payload["policy_step"] == 10
    trace.disable()
    certify(ckpt, crc32=123, size=456)
    with open(certified_sidecar(ckpt)) as f:
        assert "trace_id" not in json.load(f)


# --------------------------------------------------------------------------- #
# metrics fabric: registry + exposition
# --------------------------------------------------------------------------- #


def test_registry_merges_providers_and_isolates_crashes():
    tel_registry.register("good", lambda: {"Serve/ok": 3})
    tel_registry.register("bad", lambda: 1 / 0)
    snap = tel_registry.collect()
    assert snap["Serve/ok"] == 3
    assert snap["Telemetry/provider_errors"] == 1
    tel_registry.unregister("bad")
    assert "Telemetry/provider_errors" not in tel_registry.collect()
    assert tel_registry.providers() == ("good",)


def test_default_providers_cover_compile_trace_and_device():
    tel_registry.register_default_providers()
    assert set(tel_registry.providers()) >= {"compile", "device", "trace"}
    snap = tel_registry.collect()
    assert snap["Telemetry/enabled"] == 0  # tracer disabled by the fixture
    assert isinstance(snap["Compile/retraces"], (int, float))
    assert snap["Device/count"] >= 1


def test_prometheus_exposition_names_types_and_run_info():
    trace.configure(trace_id="beef0001")
    text = tel_export.to_prometheus(
        {"Serve/latency_p50_ms": 1.5, "Compile/retraces": 0, "Serve/source": "a-string"},
        extra_labels={"plane": "serve"},
    )
    lines = text.splitlines()
    assert 'sheeprl_run_info{plane="serve",trace_id="beef0001"} 1' in lines
    assert "# TYPE sheeprl_serve_latency_p50_ms gauge" in lines
    assert "sheeprl_serve_latency_p50_ms 1.5" in lines
    assert "sheeprl_compile_retraces 0" in lines
    assert not any("a-string" in ln for ln in lines)  # strings are not series
    assert tel_export.sanitize_name("Serve/latency+p50 ms") == "sheeprl_serve_latency_p50_ms"


def test_jsonl_sink_appends_snapshot_rows(tmp_path):
    tel_registry.register("x", lambda: {"Serve/ok": 1})
    trace.configure(trace_id="51deca5e")
    sink = tel_export.JsonlSink(str(tmp_path / "metrics.jsonl"), interval_s=3600)
    sink.flush()
    sink.stop()  # final flush; thread never started, stop() must still work
    rows = [json.loads(ln) for ln in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert len(rows) == 2 and sink.lines_written == 2
    assert rows[0]["metrics"]["Serve/ok"] == 1
    assert rows[0]["trace_id"] == "51deca5e"


# --------------------------------------------------------------------------- #
# device introspection + MFU arithmetic
# --------------------------------------------------------------------------- #


def test_chip_peak_table_and_mfu_arithmetic():
    import types

    v5e = types.SimpleNamespace(device_kind="TPU v5e")
    assert tel_device.chip_peak_flops(v5e) == 197e12
    assert tel_device.mfu(197e12, 1.0, v5e) == pytest.approx(1.0)
    assert tel_device.mfu(98.5e12, 1.0, v5e) == pytest.approx(0.5)
    unknown = types.SimpleNamespace(device_kind="Quantum Abacus")
    assert tel_device.chip_peak_flops(unknown) is None
    assert tel_device.mfu(1e12, 1.0, unknown) is None  # never fabricate a peak
    assert tel_device.mfu(None, 1.0, v5e) is None
    assert tel_device.mfu(1e12, 0.0, v5e) is None


def test_hbm_gauges_report_device_count_on_cpu():
    gauges = tel_device.hbm_gauges()
    assert gauges["Device/count"] == 8.0  # conftest forces the 8-device mesh


def test_capture_window_single_slot_and_finally_safety(monkeypatch, tmp_path):
    started, stopped = [], []

    class _FakeProfiler:
        @staticmethod
        def start_trace(d):
            started.append(d)

        @staticmethod
        def stop_trace():
            stopped.append(True)

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler)
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    assert tel_device.start_capture(d1) is True
    assert tel_device.capture_active()
    assert tel_device.start_capture(d2) is False  # one trace per process
    assert tel_device.toggle_capture(d1) == "stopped"
    assert not tel_device.capture_active()
    with pytest.raises(RuntimeError, match="mid-window"):
        with tel_device.CaptureWindow(d2):
            raise RuntimeError("mid-window")
    assert started == [d1, d2] and len(stopped) == 2  # __exit__ closed the window
    assert tel_device.stop_capture() is None  # idempotent when idle


def test_guarded_fn_captures_cost_analysis_flops():
    import jax.numpy as jnp

    from sheeprl_tpu.core import compile as jax_compile

    gfn = jax_compile.guarded_jit(lambda x: (x * 2.0 + 1.0).sum(), name="telemetry_test.flops")
    spec = jax_compile.spec_like(jnp.ones((128, 128), jnp.float32))
    gfn.aot_compile(spec)
    stats = gfn.stats()
    assert "step_flops" in stats and "flops_dispatched" in stats
    assert jax_compile.step_flops("telemetry_test.flops") == gfn.last_step_flops
    if gfn.last_step_flops is not None:  # cost_analysis is backend-dependent
        assert gfn.last_step_flops > 0
        gfn(jnp.ones((128, 128), jnp.float32))
        assert gfn.flops_dispatched == pytest.approx(gfn.last_step_flops)


# --------------------------------------------------------------------------- #
# serve stats: bounded latency reservoir + window gauges (the small fix)
# --------------------------------------------------------------------------- #


def test_serve_stats_latency_reservoir_is_bounded():
    from sheeprl_tpu.serve.stats import ServeStats

    stats = ServeStats(latency_window=8)
    for ms in range(100):  # old observations must be evicted, not accumulated
        stats.observe_latency(ms / 1000.0)
    snap = stats.snapshot()
    assert snap["Serve/latency_window_size"] == 8
    assert snap["Serve/latency_window_cap"] == 8
    # percentiles cover ONLY the last 8 observations (92..99 ms)
    assert snap["Serve/latency_p50_ms"] == pytest.approx(96.0)
    assert snap["Serve/latency_p99_ms"] == pytest.approx(99.0)


def test_serve_stats_snapshot_resort_only_when_dirty():
    from sheeprl_tpu.serve.stats import ServeStats

    stats = ServeStats(latency_window=4)
    stats.observe_latency(0.002)
    stats.observe_latency(0.001)
    first = stats.snapshot()
    assert first["Serve/latency_p50_ms"] == pytest.approx(2.0)
    assert not stats._lat_dirty
    cached = stats._lat_sorted
    assert stats.snapshot()["Serve/latency_p50_ms"] == pytest.approx(2.0)
    assert stats._lat_sorted is cached  # idle stats polling re-uses the sort
    stats.observe_latency(0.005)
    assert stats.snapshot()["Serve/latency_window_size"] == 3


# --------------------------------------------------------------------------- #
# the accelerator guarantee: span recording adds NO host<->device traffic
# --------------------------------------------------------------------------- #


@pytest.mark.timeout(300)
def test_span_recording_adds_no_host_transfers_to_a_warm_fused_iteration():
    """A warm fused PPO iteration wrapped in spans (the exact seams ppo.py
    uses) runs under ``jax.transfer_guard("disallow")`` with the tracer
    RECORDING: span timestamps/ids are pure host work, so instrumentation must
    introduce zero implicit pulls or uploads."""
    import gymnasium as gym
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import make_update_impl
    from sheeprl_tpu.config import instantiate, load_config
    from sheeprl_tpu.core.runtime import build_runtime
    from sheeprl_tpu.envs import ingraph as ig
    from sheeprl_tpu.utils.optim import with_clipping
    from sheeprl_tpu.utils.utils import PlayerParamsSync

    n_envs, t_steps = 16, 8
    n_data = n_envs * t_steps
    cfg = load_config(
        overrides=[
            "exp=ppo",
            "env=jax_cartpole",
            f"env.num_envs={n_envs}",
            f"algo.rollout_steps={t_steps}",
            f"algo.per_rank_batch_size={n_data}",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "seed=7",
        ]
    )
    runtime = build_runtime(cfg.fabric)
    venv = ig.make_vector_env(cfg, n_envs, 7, device=runtime.device)
    space = venv.single_action_space
    assert isinstance(space, gym.spaces.Discrete)
    agent, params, player = build_agent(
        runtime, (int(space.n),), False, cfg, venv.single_observation_space, None
    )
    player.params = jax.device_put(player.params, runtime.device)
    venv.reset(seed=7)
    collector = ig.InGraphRolloutCollector(
        venv, player, rollout_steps=t_steps, gamma=float(cfg.algo.gamma), name="tel_zt"
    )
    tx = with_clipping(instantiate(dict(cfg.algo.optimizer))(), cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    update_impl = make_update_impl(
        agent, tx, cfg, runtime, n_data, ["state"], [], PlayerParamsSync(player.params)
    )
    trainer = ig.FusedInGraphTrainer(collector, update_impl, n_extras=3, name="tel_zt")
    extras = (jnp.float32(cfg.algo.clip_coef), jnp.float32(cfg.algo.ent_coef), jnp.float32(1.0))
    k0, k1, k2 = (k for k in jax.random.split(jax.random.PRNGKey(5), 3))

    params, opt_state, flat, _r, _t = trainer.step(params, opt_state, k0, *extras)
    jax.block_until_ready(flat)

    tracer = trace.configure(plane="train", trace_id="zerotraffic")
    with jax.transfer_guard("disallow"):
        for i, k in enumerate((k1, k2)):
            with trace.span("train/update", fused=True, iter=i):
                params, opt_state, flat, _r, _t = trainer.step(params, opt_state, k, *extras)
            trace.instant("train/iter_done", iter=i)
        jax.block_until_ready(flat)  # fence only — not a transfer
        with pytest.raises(Exception):
            jnp.add(flat, 1.0)  # implicit host->device upload: guard is live
    assert tracer.stats()["Telemetry/spans_recorded"] == 4
    names = [ev[trace._EV_NAME] for ev in tracer.events()]
    assert names.count("train/update") == 2 and names.count("train/iter_done") == 2
    venv.close()
