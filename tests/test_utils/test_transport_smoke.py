"""Satellite registration of scripts/transport_smoke.py as a tier-1 test: a
two-process chunk stream over the host control plane must survive
failpoint-injected drops, delayed acks, torn payloads, and a mid-stream
player kill/restart — with the dead incarnation's forged zombie write fenced
by the session epoch and zero chunks lost or duplicated (full harness, fresh
interpreters, real kill delivery)."""

import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.faults
@pytest.mark.timeout(240)
def test_transport_smoke_kill_restart_roundtrip():
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "transport_smoke.py"),
            "--total",
            "12",
            "--crash-after",
            "4",
            "--timeout",
            "180",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=220,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-2000:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "transport smoke OK" in out.stdout
    assert "zombie write(s) fenced" in out.stdout
