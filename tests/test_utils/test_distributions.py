import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops.distributions import (
    Bernoulli,
    BernoulliSafeMode,
    Categorical,
    Independent,
    MSEDistribution,
    MultiCategorical,
    Normal,
    OneHotCategorical,
    OneHotCategoricalStraightThrough,
    SymlogDistribution,
    TanhNormal,
    TruncatedNormal,
    TwoHotEncodingDistribution,
)

KEY = jax.random.PRNGKey(0)


def test_normal_log_prob_matches_torch():
    import torch

    loc, scale = 0.3, 1.7
    x = 0.9
    ours = float(Normal(jnp.array(loc), jnp.array(scale)).log_prob(jnp.array(x)))
    theirs = float(torch.distributions.Normal(loc, scale).log_prob(torch.tensor(x)))
    assert ours == pytest.approx(theirs, rel=1e-4)


def test_normal_entropy_matches_torch():
    import torch

    ours = float(Normal(jnp.array(0.0), jnp.array(2.5)).entropy())
    theirs = float(torch.distributions.Normal(0.0, 2.5).entropy())
    assert ours == pytest.approx(theirs, rel=1e-4)


def test_independent_sums_event_dims():
    d = Independent(Normal(jnp.zeros((2, 3)), jnp.ones((2, 3))), 1)
    lp = d.log_prob(jnp.zeros((2, 3)))
    assert lp.shape == (2,)


def test_tanh_normal_log_prob_consistency():
    d = TanhNormal(jnp.array([0.2]), jnp.array([0.5]))
    a, logp = d.rsample_and_log_prob(KEY)
    assert jnp.all(jnp.abs(a) <= 1.0)
    lp2 = d.log_prob(a)
    np.testing.assert_allclose(np.asarray(logp), np.asarray(lp2), rtol=1e-3, atol=1e-4)


def test_truncated_normal_support():
    d = TruncatedNormal(jnp.array([0.0]), jnp.array([2.0]))
    s = d.rsample(KEY, (1000,))
    assert float(s.min()) >= -1.0 and float(s.max()) <= 1.0
    assert jnp.isneginf(d.log_prob(jnp.array([1.5]))).all()


def test_truncated_normal_matches_torchrl_style_entropy_sign():
    d = TruncatedNormal(jnp.array([0.0]), jnp.array([1.0]))
    assert jnp.isfinite(d.entropy()).all()
    assert float(d.mean[0]) == pytest.approx(0.0, abs=1e-6)


def test_categorical_matches_torch():
    import torch

    logits = [0.1, 1.2, -0.7]
    ours = Categorical(jnp.array(logits))
    theirs = torch.distributions.Categorical(logits=torch.tensor(logits))
    assert float(ours.entropy()) == pytest.approx(float(theirs.entropy()), rel=1e-4)
    assert float(ours.log_prob(jnp.array(1))) == pytest.approx(float(theirs.log_prob(torch.tensor(1))), rel=1e-4)


def test_one_hot_categorical():
    d = OneHotCategorical(logits=jnp.array([[0.0, 2.0, 0.0]]))
    s = d.sample(KEY)
    assert s.shape == (1, 3)
    assert float(s.sum()) == 1.0
    assert int(jnp.argmax(d.mode)) == 1


def test_straight_through_gradient_flows():
    def f(logits):
        d = OneHotCategoricalStraightThrough(logits=logits)
        return (d.rsample(KEY) * jnp.array([1.0, 2.0, 3.0])).sum()

    g = jax.grad(f)(jnp.array([0.5, 0.2, 0.1]))
    assert np.abs(np.asarray(g)).sum() > 0  # gradients flow through probs


def test_multi_categorical():
    d = MultiCategorical([jnp.array([[0.0, 1.0]]), jnp.array([[1.0, 0.0, 0.0]])])
    s = d.sample(KEY)
    assert s.shape == (1, 2)
    lp = d.log_prob(s.astype(jnp.int32))
    assert lp.shape == (1,)


def test_bernoulli_safe_mode():
    d = BernoulliSafeMode(logits=jnp.array([2.0, -2.0]))
    np.testing.assert_array_equal(np.asarray(d.mode), [1.0, 0.0])


def test_bernoulli_log_prob_matches_torch():
    import torch

    ours = float(Bernoulli(jnp.array(0.7)).log_prob(jnp.array(1.0)))
    theirs = float(torch.distributions.Bernoulli(logits=torch.tensor(0.7)).log_prob(torch.tensor(1.0)))
    assert ours == pytest.approx(theirs, rel=1e-3)


def test_symlog_distribution():
    mode = jnp.zeros((4, 3))
    d = SymlogDistribution(mode, dims=1)
    lp = d.log_prob(jnp.zeros((4, 3)))
    assert lp.shape == (4,)
    np.testing.assert_allclose(np.asarray(lp), 0.0)
    assert float(d.log_prob(jnp.ones((4, 3))).sum()) < 0


def test_mse_distribution():
    d = MSEDistribution(jnp.ones((2, 5)), dims=1)
    lp = d.log_prob(jnp.zeros((2, 5)))
    np.testing.assert_allclose(np.asarray(lp), -5.0)


def test_two_hot_distribution_mean_and_log_prob():
    logits = jnp.zeros((2, 255))
    d = TwoHotEncodingDistribution(logits, dims=1)
    assert d.mean.shape == (2, 1)
    np.testing.assert_allclose(np.asarray(d.mean), 0.0, atol=1e-4)
    lp = d.log_prob(jnp.array([[3.0], [0.0]]))
    assert lp.shape == (2,)
    # uniform logits: log_prob of any scalar is log(1/255)
    np.testing.assert_allclose(np.asarray(lp), np.log(1 / 255), rtol=1e-4)


def test_two_hot_distribution_peaked_recovers_value():
    # construct logits strongly peaked at the two-hot encoding of 5.0
    from sheeprl_tpu.utils.utils import symlog

    bins = jnp.linspace(-20, 20, 255)
    target = 5.0
    idx = int(jnp.argmin(jnp.abs(bins - symlog(jnp.array(target)))))
    logits = jnp.full((255,), -20.0).at[idx].set(20.0)
    d = TwoHotEncodingDistribution(logits[None], dims=1)
    decoded = float(d.mean[0, 0])
    expected = float(jnp.sign(bins[idx]) * (jnp.exp(jnp.abs(bins[idx])) - 1))
    assert decoded == pytest.approx(expected, rel=1e-2)
