import os

import pytest

from sheeprl_tpu.config import ConfigError, compose, instantiate
from sheeprl_tpu.utils.utils import dotdict


def test_compose_ppo_defaults():
    cfg = compose(overrides=["exp=ppo"])
    assert cfg.algo.name == "ppo"
    assert cfg.env.id == "CartPole-v1"
    assert cfg.buffer.size == cfg.algo.rollout_steps
    assert isinstance(cfg.algo.optimizer.lr, float)
    assert cfg.algo.encoder.dense_units == cfg.algo.dense_units


def test_cli_overrides_win():
    cfg = compose(overrides=["exp=ppo", "algo.rollout_steps=7", "seed=123"])
    assert cfg.algo.rollout_steps == 7
    assert cfg.buffer.size == 7  # interpolation resolved after overrides
    assert cfg.seed == 123


def test_group_swap():
    cfg = compose(overrides=["exp=ppo", "env=dummy"])
    assert cfg.env.id == "discrete_dummy"


def test_missing_exp_raises():
    with pytest.raises(ConfigError):
        compose(overrides=[])


def test_missing_mandatory_value_raises():
    with pytest.raises(ConfigError, match="algo.total_steps"):
        compose(overrides=["exp=default", "algo.name=x", "algo.per_rank_batch_size=1", "buffer.size=1", "env=dummy"])


def test_instantiate_partial():
    fn = instantiate({"_target_": "sheeprl_tpu.utils.optim.adam", "_partial_": True, "lr": 0.5})
    tx = fn()
    assert hasattr(tx, "init") and hasattr(tx, "update")


def test_search_path_env(tmp_path, monkeypatch):
    exp_dir = tmp_path / "exp"
    exp_dir.mkdir()
    (exp_dir / "custom.yaml").write_text(
        "# @package _global_\ndefaults:\n  - override /algo: ppo\n  - override /env: dummy\n"
        "algo:\n  total_steps: 1\n  per_rank_batch_size: 1\nbuffer:\n  size: 4\n"
    )
    monkeypatch.setenv("SHEEPRL_SEARCH_PATH", f"file://{tmp_path}")
    cfg = compose(overrides=["exp=custom"])
    assert cfg.algo.total_steps == 1
    assert cfg.env.id == "discrete_dummy"


def test_unmounted_group_selection_warns_not_errors(tmp_path, monkeypatch):
    # A packaged selection addressing a group that exists on the search path but is
    # never mounted in this composition (e.g. its enclosing group selected away) is
    # inactive, not a typo: composition proceeds with a warning (ConfigError is
    # reserved for addressing a *composed* group at a wrong package).
    plugin_dir = tmp_path / "plugin"
    plugin_dir.mkdir()
    (plugin_dir / "opt.yaml").write_text("enabled: true\n")
    monkeypatch.setenv("SHEEPRL_SEARCH_PATH", f"file://{tmp_path}")
    with pytest.warns(UserWarning, match="no mount"):
        cfg = compose(overrides=["exp=ppo", "plugin@algo.plugin=opt"])
    assert cfg.algo.name == "ppo"
    assert "plugin" not in cfg.algo


def test_dotdict_attribute_access():
    d = dotdict({"a": {"b": {"c": 1}}, "l": [{"x": 2}]})
    assert d.a.b.c == 1
    assert d.l[0].x == 2
    d.a.b.c = 5
    assert d["a"]["b"]["c"] == 5
    plain = d.as_dict()
    assert type(plain["a"]) is dict
