"""Registration of scripts/serve_fleet_smoke.py: the replica-fleet chaos
drill — 3 supervised serve replicas behind the failover router under sustained
mixed-priority closed-loop load, through a priority-aware shed burst, a
mid-load SIGKILL (failover + epoch-bumped respawn), a rolled-back-then-landed
rolling certified deploy, a forged zombie-generation membership write that the
router fences without dialing, and a fleet-wide SIGTERM drain — with every
request id resolving to exactly one terminal status and zero non-shed losses.

Marked ``slow``: the drill boots ~9 real serve replica incarnations (one JAX
interpreter each) and runs ~70 s, which does not fit the tier-1 wall-clock
budget. The tier-1 `-m fleet` tests in test_serve_fleet.py cover the same
supervisor/router/drain contracts against stub replicas; run this drill
explicitly (`-m slow`, or the script directly) before touching the fleet
plane's process-management or deploy seams."""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.fleet
@pytest.mark.timeout(600)
def test_serve_fleet_smoke_chaos_drill(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "serve_fleet_smoke.py"),
            "--workdir",
            str(tmp_path),
            "--timeout",
            "520",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-2500:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "fleet smoke OK" in out.stdout
    # the drill's own assertions already ran; independently re-audit the
    # shutdown snapshot it leaves behind
    with open(tmp_path / "fleet_stats.json") as f:
        stats = json.load(f)
    assert stats["drained"] is True, stats
    terminal = (
        stats["Fleet/ok"]
        + stats["Fleet/shed"]
        + stats["Fleet/rejected"]
        + stats["Fleet/deadline_missed"]
        + stats["Fleet/errors"]
    )
    assert stats["Fleet/requests_total"] == terminal, stats
    assert stats["Fleet/ok"] > 0, stats
    assert stats["Fleet/errors"] == 0, stats
    # the chaos actually happened: a crash-respawn, a canary rollback, a
    # landed deploy, and at least one fenced zombie write
    assert stats["Fleet/replica_restarts"] >= 1, stats
    assert stats["Fleet/deploy_rollbacks"] >= 1, stats
    assert stats["Fleet/deploys"] >= 1, stats
    assert stats["Fleet/fenced_writes"] >= 1, stats
    # every FINAL replica incarnation drained to rc 0 with its own clean books
    finals = [r for r in stats["replicas"] if r["final"]]
    assert len(finals) == 3, stats["replicas"]
    for row in finals:
        assert row["rc"] == 0, row
        rstats = row["stats"]
        assert rstats["drained"] is True, row
        rterminal = (
            rstats["Serve/ok"]
            + rstats["Serve/shed"]
            + rstats["Serve/rejected"]
            + rstats["Serve/deadline_missed"]
            + rstats["Serve/errors"]
        )
        assert rstats["Serve/requests_total"] == rterminal, row
        assert rstats["Compile/retraces"] == 0, row
