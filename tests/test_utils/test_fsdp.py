"""fabric.strategy=fsdp: sharded param placement is numerically identical to DDP.

The FSDP strategy (core/runtime.py:shard_model_params) shards every divisible
param/opt-state leaf over the ``data`` axis; XLA's SPMD partitioner inserts the
all-gathers. Reference counterpart: Fabric's sharded strategies
(sheeprl/configs/fabric/ddp.yaml family) — here it is a placement decision, not
a wrapper.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.config.loader import load_config
from sheeprl_tpu.core.runtime import Runtime


def _tiny_dv3_cfg():
    return load_config(
        overrides=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            "env.num_envs=1",
            "algo.per_rank_batch_size=8",
            "algo.per_rank_sequence_length=4",
            "algo.horizon=4",
            "algo.dense_units=16",
            "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=2",
            "algo.world_model.recurrent_model.recurrent_state_size=16",
            "algo.world_model.representation_model.hidden_size=16",
            "algo.world_model.transition_model.hidden_size=16",
            "algo.world_model.discrete_size=4",
            "algo.world_model.stochastic_size=4",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
            "fabric.precision=32-true",
        ]
    )


def test_shard_model_params_layout():
    runtime = Runtime(accelerator="cpu", devices=8, strategy="fsdp")
    tree = {
        "big": jnp.zeros((64, 32)),  # 64 % 8 == 0 -> sharded on dim 0
        "odd": jnp.zeros((7, 3)),  # indivisible -> replicated
        "scalar": jnp.float32(1.0),
    }
    placed = runtime.place_params(tree)
    from jax.sharding import PartitionSpec as P

    assert tuple(placed["big"].sharding.spec) in ((("data",)), ("data", None))
    assert all(axis is None for axis in placed["odd"].sharding.spec)
    assert all(axis is None for axis in placed["scalar"].sharding.spec)
    # each device holds 1/8 of the sharded leaf
    assert placed["big"].addressable_shards[0].data.shape == (8, 32)


def test_fsdp_explicit_kernel_specs():
    """Kernels shard their OUTPUT dim (contractions stay local); LayerNorm
    scale/bias replicate even when divisible; optax state paths inherit the
    same rules (the mu/nu trees embed the param names)."""
    runtime = Runtime(accelerator="cpu", devices=8, strategy="fsdp")
    tree = {
        "recurrent_model": {"gates": {"kernel": jnp.zeros((1040, 1536)), "bias": jnp.zeros((1536,))}},
        "enc": {"LayerNorm_0": {"scale": jnp.zeros((512,)), "bias": jnp.zeros((512,))}},
        "conv": {"kernel": jnp.zeros((4, 4, 48, 96))},
        # contraction dim (0) is the largest divisible dim, but the kernel rule
        # must still pick the OUTPUT dim (1)
        "skewed": {"kernel": jnp.zeros((4096, 8))},
    }
    placed = runtime.place_params(tree)
    assert tuple(placed["recurrent_model"]["gates"]["kernel"].sharding.spec) == (None, "data")
    assert all(a is None for a in placed["recurrent_model"]["gates"]["bias"].sharding.spec)
    assert all(a is None for a in placed["enc"]["LayerNorm_0"]["scale"].sharding.spec)
    assert tuple(placed["conv"]["kernel"].sharding.spec) == (None, None, None, "data")
    assert tuple(placed["skewed"]["kernel"].sharding.spec) == (None, "data")
    # optax-style nesting still sees the param path
    import optax

    tx = optax.adam(1e-3)
    opt_state = tx.init({"dense": {"kernel": jnp.zeros((256, 512))}})
    placed_opt = runtime.place_params(opt_state)
    mu = placed_opt[0].mu["dense"]["kernel"]
    assert tuple(mu.sharding.spec) == (None, "data")


def test_fsdp_train_step_matches_ddp():
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_fn
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments

    cfg = _tiny_dv3_cfg()
    obs_space = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (3, cfg.env.screen_size, cfg.env.screen_size), np.uint8),
            "state": gym.spaces.Box(-np.inf, np.inf, (4,), np.float32),
        }
    )
    actions_dim = (2,)

    rng = np.random.default_rng(0)
    g, t, b, a = 1, 4, 8, 2
    s = cfg.env.screen_size
    batches = {
        "rgb": rng.integers(0, 255, (g, t, b, 3, s, s), dtype=np.uint8),
        "state": rng.random((g, t, b, 4), dtype=np.float32),
        "actions": rng.random((g, t, b, a), dtype=np.float32),
        "rewards": rng.random((g, t, b, 1), dtype=np.float32),
        "terminated": np.zeros((g, t, b, 1), dtype=np.float32),
        "truncated": np.zeros((g, t, b, 1), dtype=np.float32),
        "is_first": np.zeros((g, t, b, 1), dtype=np.float32),
    }
    key = jax.random.PRNGKey(0)

    results = {}
    for strategy in ("auto", "fsdp"):
        runtime = Runtime(accelerator="cpu", devices=8, strategy=strategy, precision="32-true")
        modules, params, _ = build_agent(runtime, actions_dim, False, cfg, obs_space)
        init_opt, train_fn = make_train_fn(modules, cfg, runtime, False, actions_dim)
        opt_states = runtime.place_params(init_opt(params))
        params = runtime.place_params(params)
        moments = init_moments()
        from jax.sharding import NamedSharding, PartitionSpec as P

        batch_sh = NamedSharding(runtime.mesh, P(None, None, "data"))
        dev_batches = {k: jax.device_put(jnp.asarray(v), batch_sh) for k, v in batches.items()}
        new_params, _, _, counter, _flat, metrics = train_fn(
            params, opt_states, moments, jnp.int32(0), dev_batches, key
        )
        results[strategy] = (
            jax.device_get(metrics["Loss/world_model_loss"]),
            jax.device_get(new_params["actor"]),
            int(counter),
        )

    loss_a, actor_a, c_a = results["auto"]
    loss_f, actor_f, c_f = results["fsdp"]
    assert c_a == c_f == 1
    np.testing.assert_allclose(loss_a, loss_f, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-4, atol=1e-5),
        actor_a,
        actor_f,
    )


def test_dv3_cli_with_fsdp(tmp_path, monkeypatch):
    """End-to-end DV3 smoke at fabric.strategy=fsdp over 2 devices."""
    monkeypatch.chdir(tmp_path)
    from sheeprl_tpu.cli import run

    run(
        overrides=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "dry_run=True",
            "metric.log_level=0",
            "checkpoint.save_last=False",
            "fabric.devices=2",
            "fabric.strategy=fsdp",
            "algo.learning_starts=0",
            "algo.per_rank_sequence_length=1",
            "algo.per_rank_batch_size=2",
            "algo.dense_units=16",
            "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=2",
            "algo.world_model.recurrent_model.recurrent_state_size=16",
            "algo.world_model.representation_model.hidden_size=16",
            "algo.world_model.transition_model.hidden_size=16",
            "algo.world_model.discrete_size=4",
            "algo.world_model.stochastic_size=4",
            "algo.horizon=4",
        ]
    )
