import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.utils.utils import symexp, symlog, two_hot_decoder, two_hot_encoder


@pytest.mark.parametrize("value", [-250.0, -17.3, -1.0, 0.0, 0.5, 1.0, 42.0, 299.0])
def test_two_hot_round_trip(value):
    encoded = two_hot_encoder(jnp.array([value]), support_range=300, num_buckets=255)
    assert encoded.shape == (255,)
    np.testing.assert_allclose(float(encoded.sum()), 1.0, rtol=1e-5)
    decoded = two_hot_decoder(encoded, support_range=300)
    np.testing.assert_allclose(float(decoded[0]), value, rtol=2e-2, atol=1e-2)


def test_two_hot_batched_shapes():
    values = jnp.ones((4, 8, 1)) * 3.0
    enc = two_hot_encoder(values, 300, 255)
    assert enc.shape == (4, 8, 255)
    dec = two_hot_decoder(enc, 300)
    assert dec.shape == (4, 8, 1)


def test_two_hot_at_most_two_nonzero():
    enc = np.asarray(two_hot_encoder(jnp.array([17.3]), 300, 255))
    assert (enc > 0).sum() <= 2


def test_symlog_symexp_inverse():
    x = jnp.array([-1000.0, -1.0, 0.0, 0.1, 500.0])
    np.testing.assert_allclose(np.asarray(symexp(symlog(x))), np.asarray(x), rtol=1e-5, atol=1e-5)
