"""Satellite registration of scripts/health_smoke.py as a tier-1 test: a
reward-spike fault injected mid-run must be detected by the health sentinel,
climb the warn -> backoff -> rollback ladder, restore a certified (last_good)
checkpoint, and let the run complete cleanly (full harness, fresh
interpreter)."""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.timeout(600)
def test_health_smoke_divergence_rollback_roundtrip(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "health_smoke.py"),
            "--workdir",
            str(tmp_path),
            "--timeout",
            "480",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-1500:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "health smoke OK" in out.stdout
    # the harness's own assertions already ran; re-check the event log records
    # the full ladder and that the rollback restored a CERTIFIED checkpoint
    events_files = [
        os.path.join(base, f)
        for base, _, fs in os.walk(tmp_path / "logs")
        for f in fs
        if f == "events.jsonl"
    ]
    assert len(events_files) == 1
    with open(events_files[0]) as f:
        events = [json.loads(line) for line in f if line.strip()]
    kinds = [e["event"] for e in events]
    assert "rollback" in kinds, kinds
    rollback = next(e for e in events if e["event"] == "rollback")
    assert rollback["path"].endswith(".ckpt") and rollback["wall_s"] >= 0, rollback
    # the rollback target's own sidecar may since have been aged out by the
    # certified GC budget, but the healthy post-recovery tail must have left
    # certified checkpoints behind
    assert any(
        f.endswith(".certified.json") for _, _, fs in os.walk(tmp_path / "logs") for f in fs
    ), "no certified (last_good) sidecars on disk at end of run"
