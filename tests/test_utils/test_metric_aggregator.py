"""MetricAggregator semantics + actor-class resolution (ADVICE round-1 items)."""

import jax.numpy as jnp
import pytest

from sheeprl_tpu.utils.metric import MeanMetric, MetricAggregator
from sheeprl_tpu.utils.utils import resolve_actor_cls


def test_update_from_device_filters_unregistered_keys():
    agg = MetricAggregator({"Loss/a": MeanMetric()}, raise_on_missing=True)
    # Train loops pass the full train-metrics dict; extra keys must be ignored,
    # not raised on, even with raise_on_missing=True.
    agg.update_from_device({"Loss/a": jnp.float32(2.0), "Loss/unregistered": jnp.float32(9.0)})
    out = agg.compute()
    assert out == {"Loss/a": 2.0}


def test_update_raise_on_missing_still_guards_single_key():
    agg = MetricAggregator({"Loss/a": MeanMetric()}, raise_on_missing=True)
    with pytest.raises(KeyError):
        agg.update("Loss/nope", 1.0)


def test_update_from_device_mixed_host_device_values():
    agg = MetricAggregator({"a": MeanMetric(), "b": MeanMetric()})
    agg.update_from_device({"a": 1.0, "b": jnp.float32(3.0)})
    assert agg.compute() == {"a": 1.0, "b": 3.0}


class _Default:
    pass


class _Minedojo:
    pass


@pytest.mark.parametrize(
    "path, expected",
    [
        (None, _Default),
        ("", _Default),
        ("sheeprl_tpu.algos.dreamer_v3.agent.Actor", _Default),
        ("sheeprl_tpu.algos.dreamer_v2.agent.ActorDV2", _Default),
        ("sheeprl_tpu.algos.dreamer_v3.agent.MinedojoActor", _Minedojo),
        ("sheeprl_tpu.algos.dreamer_v2.agent.MinedojoActorDV2", _Minedojo),
        ("sheeprl.algos.dreamer_v3.agent.MinedojoActor", _Minedojo),
    ],
)
def test_resolve_actor_cls(path, expected):
    assert resolve_actor_cls(path, _Default, _Minedojo) is expected


def test_resolve_actor_cls_rejects_unknown():
    with pytest.raises(ValueError, match="Unrecognized actor cls"):
        resolve_actor_cls("some.module.WeirdActor", _Default, _Minedojo)
