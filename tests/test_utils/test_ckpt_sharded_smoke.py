"""Satellite registration of scripts/ckpt_sharded_smoke.py as a tier-1 test: a
two-host sharded checkpoint fleet must commit healthy generations atomically,
leave NO visible generation when a host is killed before the commit barrier
(``ckpt.commit`` and ``ckpt.shard_write`` failpoints, real kill delivery),
fence a zombie writer's late commit via the session epoch, garbage-collect the
abandoned shard directories, and restore a restarted host from a peer's RAM
replica with zero persistent-storage reads (full harness, fresh
interpreters)."""

import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.faults
@pytest.mark.timeout(240)
def test_ckpt_sharded_smoke_kill_commit_peer_restore():
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "ckpt_sharded_smoke.py"),
            "--timeout",
            "180",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=220,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-2000:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "ckpt sharded smoke OK" in out.stdout
    assert "0 storage reads" in out.stdout
    assert "[200, 250] discarded" in out.stdout
