"""Satellite registration of scripts/ingraph_smoke.py as a tier-1 test: a
fresh-interpreter PPO run on ``env.backend=ingraph`` must finish with zero
retraces and a random-policy drive through the debug step path must play
finite-return episodes — the cheapest end-to-end proof that the in-graph
backend stays wired through the config, factory, and algo layers."""

import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.ingraph
@pytest.mark.timeout(600)
def test_ingraph_smoke(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "ingraph_smoke.py"),
            "--workdir",
            str(tmp_path),
            "--timeout",
            "420",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-1500:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "ingraph smoke OK" in out.stdout
