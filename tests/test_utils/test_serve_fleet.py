"""Replica-fleet serving plane: supervisor, failover router, epoch fencing.

Fast tier-1 coverage for `sheeprl_tpu/serve/fleet.py` + `router.py`:

- router units run fully in-process (no subprocesses, no router TCP thread):
  membership fencing via direct `apply_membership`, failover across in-thread
  stub backends, deadline-bounded retries, drain admission.
- supervisor tests replace the real serve replica with a stdlib-only stub
  server through the ``SHEEPRL_TPU_SERVE_ENTRY`` seam (the same trick the
  orchestrator tests use for trainees), so a spawn costs ~100 ms instead of a
  JAX boot. The full-stack drill against real replicas lives in
  `scripts/serve_fleet_smoke.py` / `test_serve_fleet_smoke.py`.
- the `PreemptionGuard(forward_to_children=True)` fan-out drill runs the real
  `python -m sheeprl_tpu.serve.fleet` CLI and delivers SIGTERM through the
  ``fleet.heartbeat:signal`` failpoint — at a deterministic supervision tick,
  not a wall-clock race — then audits the fleet-wide zero-loss drain.
"""

import json
import os
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time

import pytest

from sheeprl_tpu.core import failpoints
from sheeprl_tpu.serve.fleet import ENTRY_ENV_VAR, FleetSupervisor, _rpc
from sheeprl_tpu.serve.router import FailoverRouter, read_membership
from sheeprl_tpu.serve.stats import FleetStats

pytestmark = pytest.mark.fleet

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# --------------------------------------------------------------------------- stats
def _fleet_counter_sum(snap):
    return (
        snap["Fleet/ok"]
        + snap["Fleet/shed"]
        + snap["Fleet/rejected"]
        + snap["Fleet/deadline_missed"]
        + snap["Fleet/errors"]
    )


def test_fleet_stats_prefix_and_terminal_invariant():
    stats = FleetStats()
    stats.inc("requests_total", 3)
    stats.inc("ok", 2)
    stats.inc("shed")
    stats.inc("failovers")
    stats.set_gauge("members", 3)
    snap = stats.snapshot()
    assert all(k.startswith("Fleet/") for k in snap)
    assert snap["Fleet/requests_total"] == 3
    assert snap["Fleet/members"] == 3
    assert _fleet_counter_sum(snap) == snap["Fleet/requests_total"]


# --------------------------------------------------------------------------- fencing
def test_router_fences_stale_epochs_and_duplicate_slots():
    stats = FleetStats()
    r = FailoverRouter("/nonexistent/membership.json", stats)
    r.apply_membership([{"slot": 0, "epoch": 3, "host": "a", "port": 1}])
    assert [(m.slot, m.epoch) for m in r.members()] == [(0, 3)]

    # duplicate slot entries (a forged file): max epoch wins, the loser is a
    # fenced write, the surviving member is untouched
    r.apply_membership(
        [
            {"slot": 0, "epoch": 3, "host": "a", "port": 1},
            {"slot": 0, "epoch": 2, "host": "zombie", "port": 66},
        ]
    )
    ms = r.members()
    assert len(ms) == 1 and ms[0].epoch == 3 and ms[0].port == 1
    assert stats.snapshot()["Fleet/fenced_writes"] == 1

    # an entire view at a stale epoch: fenced AND the live member survives —
    # a zombie write can degrade nothing
    r.apply_membership([{"slot": 0, "epoch": 2, "host": "zombie", "port": 66}])
    ms = r.members()
    assert len(ms) == 1 and ms[0].epoch == 3 and ms[0].port == 1
    assert stats.snapshot()["Fleet/fenced_writes"] == 2

    # the fence SURVIVES the member's removal: a zombie re-appearing after its
    # replacement drained is still a zombie
    r.apply_membership([])
    assert r.members() == []
    r.apply_membership([{"slot": 0, "epoch": 2, "host": "zombie", "port": 66}])
    assert r.members() == []
    assert stats.snapshot()["Fleet/fenced_writes"] == 3

    # a NEWER incarnation is welcome, and unparseable entries route nowhere
    r.apply_membership(
        [{"slot": 0, "epoch": 4, "host": "b", "port": 2}, {"epoch": "junk"}]
    )
    ms = r.members()
    assert len(ms) == 1 and ms[0].epoch == 4 and ms[0].port == 2
    snap = stats.snapshot()
    assert snap["Fleet/fenced_writes"] == 4
    assert snap["Fleet/epoch_max"] == 4


# --------------------------------------------------------------------------- relays
class _StubBackend:
    """In-thread JSON-lines replica. ``mode='ok'`` answers; ``mode='eof'``
    closes the connection on accept (a replica dying with the request on its
    wire)."""

    def __init__(self, mode="ok", name="stub"):
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                outer.hits += 1
                if outer.mode == "eof":
                    return
                line = self.rfile.readline()
                if not line:
                    return
                msg = json.loads(line)
                resp = {"id": msg.get("id"), "status": "ok", "replica": outer.name}
                self.wfile.write((json.dumps(resp) + "\n").encode())
                self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self.mode = mode
        self.name = name
        self.hits = 0
        self._srv = Server(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


def _submit_and_wait(router, msg, timeout=10.0):
    got = []
    done = threading.Event()

    def send(resp):
        got.append(resp)
        done.set()

    router.submit(msg, send)
    assert done.wait(timeout), "router never resolved the request"
    return got[0]


@pytest.mark.timeout(60)
def test_router_fails_over_to_a_live_replica():
    stats = FleetStats()
    dead = _StubBackend(mode="eof")
    live = _StubBackend(mode="ok", name="survivor")
    r = FailoverRouter("/nonexistent/membership.json", stats, retry_backoff_ms=5.0)
    try:
        r.apply_membership(
            [
                {"slot": 0, "epoch": 1, "host": "127.0.0.1", "port": dead.port},
                {"slot": 1, "epoch": 1, "host": "127.0.0.1", "port": live.port},
            ]
        )
        # least-outstanding tie-breaks to slot 0 => the dead replica is dialed
        # first, the retry MUST land on a different replica
        resp = _submit_and_wait(r, {"id": "x", "obs": [1.0]})
        assert resp["status"] == "ok"
        assert resp["replica"] == "survivor"
        assert resp["id"] == "x"
        assert dead.hits >= 1
    finally:
        r.close()
        dead.close()
        live.close()
    snap = stats.snapshot()
    assert snap["Fleet/dial_failures"] >= 1
    assert snap["Fleet/retries"] >= 1
    assert snap["Fleet/failovers"] == 1
    assert snap["Fleet/ok"] == 1
    assert _fleet_counter_sum(snap) == snap["Fleet/requests_total"] == 1


@pytest.mark.timeout(60)
def test_router_deadline_bounds_the_retry_loop():
    stats = FleetStats()
    # a port with nothing listening: every dial is refused
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    r = FailoverRouter(
        "/nonexistent/membership.json",
        stats,
        retry_budget=50,
        retry_backoff_ms=10.0,
        dial_timeout_s=0.2,
    )
    try:
        r.apply_membership([{"slot": 0, "epoch": 1, "host": "127.0.0.1", "port": dead_port}])
        t0 = time.monotonic()
        resp = _submit_and_wait(r, {"id": "d", "obs": [], "deadline_ms": 150})
        elapsed = time.monotonic() - t0
    finally:
        r.close()
    # the deadline resolves the request long before the 50-retry budget could:
    # a dead replica never turns into an unbounded client stall
    assert resp["status"] == "deadline_expired"
    assert elapsed < 5.0
    snap = stats.snapshot()
    assert snap["Fleet/deadline_missed"] == 1
    assert snap["Fleet/dial_failures"] >= 1
    assert _fleet_counter_sum(snap) == snap["Fleet/requests_total"] == 1


def test_router_drain_rejects_but_still_answers():
    stats = FleetStats()
    live = _StubBackend()
    r = FailoverRouter("/nonexistent/membership.json", stats)
    try:
        r.apply_membership([{"slot": 0, "epoch": 1, "host": "127.0.0.1", "port": live.port}])
        assert r.drain(timeout=5.0) is True
        resp = _submit_and_wait(r, {"id": "q", "obs": []}, timeout=5.0)
    finally:
        r.close()
        live.close()
    # draining still answers: exactly one terminal response, just a refusal
    assert resp["status"] == "rejected"
    assert resp["reason"] == "draining"
    assert live.hits == 0
    snap = stats.snapshot()
    assert snap["Fleet/rejected"] == 1
    assert _fleet_counter_sum(snap) == snap["Fleet/requests_total"] == 1


# --------------------------------------------------------------------------- supervisor
# Stdlib-only stand-in for a serve replica: honors the spawn contract
# (ready-file handshake, stats_file, preemption flag file, SIGTERM -> drain ->
# rc 0) and answers infer/health with its checkpoint's basename so deploys are
# observable, without paying a JAX boot per incarnation.
_STUB_REPLICA = """\
import json, os, signal, socketserver, sys, threading, time

kv = {}
for arg in sys.argv[1:]:
    key, _, value = arg.partition("=")
    kv[key] = value
ckpt = kv.get("checkpoint_path", "")
ready_file = kv["serve.server.ready_file"]
stats_file = kv.get("stats_file")
drain_s = float(kv.get("stub.drain_s", "1.0"))
flag_file = os.environ.get("SHEEPRL_PREEMPTION_FLAG_FILE")

counts = {"requests_total": 0, "ok": 0, "shed": 0, "rejected": 0,
          "deadline_missed": 0, "errors": 0}
lock = threading.Lock()
draining = threading.Event()
stop_at = [None]


class Handler(socketserver.StreamRequestHandler):
    def handle(self):
        while True:
            line = self.rfile.readline()
            if not line:
                return
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            op = msg.get("op", "infer")
            if op == "health":
                resp = {"ready": not draining.is_set(), "live": True}
            elif op == "stats":
                with lock:
                    resp = {"Serve/%s" % k: v for k, v in counts.items()}
            else:
                with lock:
                    counts["requests_total"] += 1
                    if draining.is_set():
                        counts["rejected"] += 1
                        resp = {"id": msg.get("id"), "status": "rejected",
                                "reason": "draining", "retry_after_ms": 25.0}
                    else:
                        counts["ok"] += 1
                        resp = {"id": msg.get("id"), "status": "ok",
                                "ckpt": os.path.basename(ckpt), "pid": os.getpid()}
            self.wfile.write((json.dumps(resp) + "\\n").encode())
            self.wfile.flush()


class Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


def on_signal(sig, frame):
    if flag_file:
        try:
            with open(flag_file, "w") as f:
                f.write("preempted\\n")
        except OSError:
            pass
    if stop_at[0] is None:  # keep the FIRST drain window; re-signals are no-ops
        stop_at[0] = time.monotonic() + drain_s
    draining.set()


signal.signal(signal.SIGTERM, on_signal)
signal.signal(signal.SIGINT, on_signal)
srv = Server(("127.0.0.1", 0), Handler)
threading.Thread(target=srv.serve_forever, daemon=True).start()
tmp = ready_file + ".tmp"
with open(tmp, "w") as f:
    json.dump({"host": "127.0.0.1", "port": srv.server_address[1], "pid": os.getpid()}, f)
os.replace(tmp, ready_file)
while stop_at[0] is None or time.monotonic() < stop_at[0]:
    time.sleep(0.02)
srv.shutdown()
srv.server_close()
if stats_file:
    with lock:
        payload = {"Serve/%s" % k: v for k, v in counts.items()}
    payload["Compile/retraces"] = 0
    payload["drained"] = True
    tmp = stats_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, stats_file)
sys.exit(0)
"""


@pytest.fixture
def stub_entry(tmp_path, monkeypatch):
    entry = tmp_path / "stub_replica.py"
    entry.write_text(_STUB_REPLICA)
    monkeypatch.setenv(ENTRY_ENV_VAR, str(entry))
    return entry


def _certified_ckpt(ckpt_dir, step):
    from sheeprl_tpu.utils.checkpoint import certify, save_state

    os.makedirs(str(ckpt_dir), exist_ok=True)
    path = os.path.join(str(ckpt_dir), f"ckpt_{step}_0.ckpt")
    info = save_state(path, {"agent": f"weights-{step}"})
    certify(path, crc32=info.get("crc32"), size=info.get("size"), policy_step=step)
    return path


def _make_supervisor(tmp_path, ckpt, **kw):
    opts = dict(
        replicas=2,
        serve_overrides=("stub.drain_s=0.3",),
        heartbeat_s=0.05,
        heartbeat_timeout_s=5.0,
        restart_backoff_s=0.05,
        restart_backoff_max_s=0.1,
        drain_timeout_s=20.0,
        ready_timeout_s=60.0,
        deploy_poll_s=0.1,
        deploy_retry_s=0.3,
        router_opts={"membership_poll_s": 0.02, "retry_backoff_ms": 5.0},
    )
    opts.update(kw)
    return FleetSupervisor(ckpt, str(tmp_path / "fleet"), **opts)


def _tick_until(sup, pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sup.tick()
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}; stats={sup.stats.snapshot()}")


@pytest.mark.timeout(120)
def test_supervisor_respawns_killed_replica_with_epoch_bump(stub_entry, tmp_path):
    ckpt = _certified_ckpt(tmp_path / "run" / "checkpoint", 100)
    sup = _make_supervisor(tmp_path, ckpt)
    drained = None
    try:
        sup.start()
        members = {m["slot"]: m for m in read_membership(sup.membership_file)}
        assert sorted(members) == [0, 1]
        epoch0 = members[0]["epoch"]
        router_addr = (sup.router.host, sup.router.port)
        assert _rpc(router_addr, {"id": "r1", "obs": [0.0]})["status"] == "ok"

        os.kill(sup._handles[0].pid, signal.SIGKILL)
        _tick_until(
            sup,
            lambda: sup.stats.snapshot()["Fleet/replica_restarts"] >= 1,
            timeout=30.0,
            what="the killed replica to respawn",
        )
        snap = sup.stats.snapshot()
        assert snap["Fleet/replica_failures"] == 1  # SIGKILL classified as a crash
        assert snap["Fleet/replica_restarts"] == 1
        members = {m["slot"]: m for m in read_membership(sup.membership_file)}
        assert sorted(members) == [0, 1]
        # the respawn is a NEW fenced generation: a zombie of the old one
        # could never re-enter the membership
        assert members[0]["epoch"] > epoch0
        assert _rpc(router_addr, {"id": "r2", "obs": [0.0]})["status"] == "ok"
        drained = sup.shutdown(stats_file=str(tmp_path / "fleet_stats.json"))
    finally:
        if drained is None:  # body failed: best-effort teardown, keep the error
            try:
                sup.shutdown()
            except Exception:
                pass
    assert drained is True
    stats = json.load(open(tmp_path / "fleet_stats.json"))
    assert stats["drained"] is True
    finals = [r for r in stats["replicas"] if r["final"]]
    assert len(finals) == 2
    assert all(r["rc"] == 0 and r["stats"]["drained"] for r in finals)
    # the SIGKILLed incarnation is reported but NOT audited for a clean drain
    assert any(not r["final"] and r["rc"] != 0 for r in stats["replicas"])


@pytest.mark.timeout(120)
@pytest.mark.faults
def test_supervisor_canary_rollback_then_rolling_deploy_lands(stub_entry, tmp_path):
    ckpt_dir = tmp_path / "run" / "checkpoint"
    ckpt = _certified_ckpt(ckpt_dir, 100)
    sup = _make_supervisor(tmp_path, ckpt)
    drained = None
    try:
        sup.start()
        router_addr = (sup.router.host, sup.router.port)
        new_ckpt = _certified_ckpt(ckpt_dir, 200)
        # the canary verification fails ONCE on a healthy artifact: the fleet
        # must stay on step 100, then the retry lands fleet-wide
        with failpoints.active("fleet.deploy:raise:injected-canary-drill:hit=1"):
            _tick_until(
                sup,
                lambda: sup.stats.snapshot()["Fleet/deploys"] >= 1,
                timeout=60.0,
                what="the rolling deploy to land after the canary rollback",
            )
        snap = sup.stats.snapshot()
        assert snap["Fleet/deploy_rollbacks"] == 1
        assert snap["Fleet/deploys"] == 1
        members = read_membership(sup.membership_file)
        assert len(members) == 2
        assert all(m["ckpt"] == new_ckpt and m["step"] == 200 for m in members)
        resp = _rpc(router_addr, {"id": "d1", "obs": [0.0]})
        assert resp["status"] == "ok"
        assert resp["ckpt"] == os.path.basename(new_ckpt)  # replicas really moved
        drained = sup.shutdown(stats_file=str(tmp_path / "fleet_stats.json"))
    finally:
        if drained is None:
            try:
                sup.shutdown()
            except Exception:
                pass
    assert drained is True
    stats = json.load(open(tmp_path / "fleet_stats.json"))
    assert stats["drained"] is True


# ------------------------------------------------------------------- preemption fan-out
class _DrainClient(threading.Thread):
    """Closed-loop client that keeps exactly one request outstanding and
    retries the SAME id through transport failures, so `unresolved` is the
    set of requests that never got their one terminal answer."""

    def __init__(self, addr, idx):
        super().__init__(daemon=True)
        self.addr = addr
        self.idx = idx
        self.ok = 0
        self.errors = []
        self.issued = {}
        self.stop_ev = threading.Event()

    def run(self):
        n = 0
        while not self.stop_ev.is_set():
            rid = f"c{self.idx}-{n}"
            n += 1
            self.issued[rid] = "pending"
            payload = {"id": rid, "obs": [0.0], "priority": self.idx % 2}
            while not self.stop_ev.is_set():
                try:
                    resp = _rpc(self.addr, payload, timeout=5.0)
                except (OSError, ConnectionError, ValueError):
                    time.sleep(0.02)  # router restarting/draining: same id again
                    continue
                status = resp.get("status")
                self.issued[rid] = status
                if status == "ok":
                    self.ok += 1
                elif status in ("shed", "rejected", "deadline_expired"):
                    time.sleep(0.005)
                else:
                    self.errors.append(resp)
                break

    @property
    def unresolved(self):
        return [rid for rid, st in self.issued.items() if st == "pending"]


@pytest.mark.timeout(180)
@pytest.mark.faults
def test_preemption_fanout_drains_every_replica_to_rc0(stub_entry, tmp_path):
    """`PreemptionGuard(forward_to_children=True)` fan-out: one SIGTERM at the
    supervisor — delivered by the `fleet.heartbeat:signal` failpoint at a
    deterministic supervision tick, not by a wall-clock race — drains the
    router AND every replica to rc 0 with zero in-flight losses."""
    ckpt = _certified_ckpt(tmp_path / "run" / "checkpoint", 100)
    workdir = tmp_path / "fleet"
    ready_file = tmp_path / "router_ready.json"
    stats_file = tmp_path / "fleet_stats.json"
    env = dict(
        os.environ,
        PYTHONPATH=REPO_ROOT,
        JAX_PLATFORMS="cpu",
        # evaluated once per live slot per probe round (2 slots @ 0.1 s): the
        # 9th evaluation self-SIGTERMs the supervisor mid-load on round 5
        SHEEPRL_TPU_FAILPOINTS="fleet.heartbeat:signal:SIGTERM:hit=9",
    )
    cmd = [
        sys.executable,
        "-m",
        "sheeprl_tpu.serve.fleet",
        f"checkpoint_path={ckpt}",
        f"workdir={workdir}",
        f"ready_file={ready_file}",
        f"stats_file={stats_file}",
        "fleet.replicas=2",
        "fleet.heartbeat_s=0.1",
        "fleet.drain_timeout_s=30",
        "router.membership_poll_s=0.02",
        "router.retry_backoff_ms=5.0",
        "stub.drain_s=1.0",
    ]
    log_path = tmp_path / "fleet.log"
    clients = []
    with open(log_path, "wb") as log_f:
        proc = subprocess.Popen(
            cmd, env=env, cwd=str(tmp_path), stdout=log_f, stderr=subprocess.STDOUT
        )
        try:
            deadline = time.monotonic() + 120
            while not ready_file.is_file():
                assert proc.poll() is None, (
                    f"fleet exited rc={proc.returncode} before ready:\n"
                    + log_path.read_text()[-2000:]
                )
                assert time.monotonic() < deadline, "fleet never became ready"
                time.sleep(0.05)
            info = json.loads(ready_file.read_text())
            addr = (info["host"], int(info["port"]))
            clients = [_DrainClient(addr, i) for i in range(2)]
            for c in clients:
                c.start()
            # the failpoint fires while this load is running; the fleet must
            # drain itself to a clean exit without any external stop signal
            rc = proc.wait(timeout=120)
        finally:
            for c in clients:
                c.stop_ev.set()
            for c in clients:
                c.join(timeout=10)
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
    assert rc == 0, f"fleet rc={rc}; log:\n{log_path.read_text()[-2000:]}"

    stats = json.load(open(stats_file))
    assert stats["drained"] is True
    finals = [r for r in stats["replicas"] if r["final"]]
    assert len(finals) == 2
    assert all(r["rc"] == 0 and (r.get("stats") or {}).get("drained") for r in finals)
    # the forwarded SIGTERM is a SHUTDOWN everywhere, not a crash: nothing was
    # classified as failed, nothing respawned, nothing lost
    assert stats["Fleet/replica_failures"] == 0
    assert stats["Fleet/replica_restarts"] == 0
    assert stats["Fleet/ok"] > 0
    total = stats["Fleet/requests_total"]
    assert total == (
        stats["Fleet/ok"]
        + stats["Fleet/shed"]
        + stats["Fleet/rejected"]
        + stats["Fleet/deadline_missed"]
        + stats["Fleet/errors"]
    )
    for c in clients:
        assert c.ok > 0, "client saw no successful responses before the drill"
        assert c.errors == [], f"client {c.idx} saw errors: {c.errors[:3]}"
        # exactly-one-terminal-response: at most the single request a client
        # had outstanding when the frontend went away is unresolved
        assert len(c.unresolved) <= 1, c.unresolved
