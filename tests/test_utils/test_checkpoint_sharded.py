"""Topology-elastic sharded checkpoints: resharding round-trips, torn-shard
and missing-commit rejection, partial shard reads, GC sweeps, compat gating,
the lazy host pickler's peak-RAM bound, and the async CheckpointCallback path.

The acceptance bar from the elastic-checkpointing issue: a checkpoint saved on
an ``n``-device mesh must restore BIT-IDENTICALLY on a 1/2/4/8-device mesh
(including plain host numpy assembly), an uncommitted or torn generation must
be rejected at the same corruption boundary the older-sibling fallback keys
on, and restores read only the shard windows they need.
"""

import os
import shutil

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec

import sheeprl_tpu.utils.ckpt_sharded as cs
from sheeprl_tpu.utils.checkpoint import (
    CheckpointCallback,
    CheckpointCorruptionError,
    artifact_bootable,
    certified_info,
    certify,
    is_certified,
    latest_certified,
    load_state,
    save_state,
)

MESH_SIZES = (1, 2, 4, 8)


def _mesh(n: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:n]), ("d",))


def _state(mesh: Mesh):
    """Deterministic state with a mesh-sharded leaf, a replicated jax leaf, a
    host numpy leaf with an indivisible axis, and non-array metadata."""
    rng = np.random.default_rng(7)
    w = rng.standard_normal((16, 6)).astype(np.float32)
    b = rng.standard_normal((8,)).astype(np.float32)
    sharded = jax.device_put(w, NamedSharding(mesh, PartitionSpec("d")))
    replicated = jax.device_put(b, NamedSharding(mesh, PartitionSpec()))
    return {
        "agent": {"w": sharded, "b": replicated},
        "odd": np.arange(21, dtype=np.float64).reshape(7, 3),
        "step": 41,
        "names": ["actor", "critic"],
    }


def _expect():
    rng = np.random.default_rng(7)
    return {
        "w": rng.standard_normal((16, 6)).astype(np.float32),
        "b": rng.standard_normal((8,)).astype(np.float32),
        "odd": np.arange(21, dtype=np.float64).reshape(7, 3),
    }


def _save(tmp_path, n: int, name: str = "gen.ckpt") -> str:
    path = str(tmp_path / name)
    cs.save_sharded(path, _state(_mesh(n)))
    return path


@pytest.mark.parametrize("save_n", MESH_SIZES)
@pytest.mark.parametrize("load_n", MESH_SIZES)
def test_reshard_roundtrip_bitwise(tmp_path, save_n, load_n):
    path = _save(tmp_path, save_n)
    mesh_b = _mesh(load_n)

    def sharding_for(key, shape, dtype):
        if key.endswith("/w"):
            return NamedSharding(mesh_b, PartitionSpec("d"))
        if key.endswith("/b"):
            return NamedSharding(mesh_b, PartitionSpec())
        return None  # host numpy assembly

    state = cs.elastic_restore(path, sharding_for)
    want = _expect()
    np.testing.assert_array_equal(np.asarray(state["agent"]["w"]), want["w"])
    np.testing.assert_array_equal(np.asarray(state["agent"]["b"]), want["b"])
    np.testing.assert_array_equal(state["odd"], want["odd"])
    assert state["step"] == 41 and state["names"] == ["actor", "critic"]
    # the restored leaf really lives on mesh B
    assert len(state["agent"]["w"].sharding.device_set) == load_n


@pytest.mark.parametrize("save_n", MESH_SIZES)
def test_host_numpy_assembly_bitwise(tmp_path, save_n):
    """``load_sharded`` (and ``load_state`` on a dir) assemble the full global
    state as host numpy on ANY topology — the single-device restore story."""
    path = _save(tmp_path, save_n)
    want = _expect()
    for loader in (cs.load_sharded, load_state):
        state = loader(path)
        np.testing.assert_array_equal(np.asarray(state["agent"]["w"]), want["w"])
        np.testing.assert_array_equal(np.asarray(state["agent"]["b"]), want["b"])
        np.testing.assert_array_equal(np.asarray(state["odd"]), want["odd"])
        assert state["step"] == 41


def test_namedtuple_opt_state_survives(tmp_path):
    """Optax opt states are (nested) NamedTuples — the skeleton must keep
    their classes so ``state.mu`` works after restore (a bare tuple crashed
    the first resumed train step)."""
    import optax

    params = {"w": np.ones((4, 2), np.float32)}
    opt_state = optax.adam(1e-3).init(params)
    path = str(tmp_path / "opt.ckpt")
    cs.save_sharded(path, {"params": params, "opt_state": opt_state})
    out = cs.load_sharded(path)
    restored = out["opt_state"]
    assert type(restored[0]).__name__ == type(opt_state[0]).__name__
    np.testing.assert_array_equal(np.asarray(restored[0].mu["w"]), np.asarray(opt_state[0].mu["w"]))
    np.testing.assert_array_equal(np.asarray(restored[0].nu["w"]), np.asarray(opt_state[0].nu["w"]))
    assert int(restored[0].count) == int(opt_state[0].count)


def test_missing_commit_marker_rejected(tmp_path):
    path = _save(tmp_path, 4)
    os.remove(os.path.join(path, cs.COMMIT_NAME))
    ok, why = cs.bootable(path)
    assert not ok and "commit" in why
    assert not is_certified(path)
    with pytest.raises(CheckpointCorruptionError, match="commit marker"):
        cs.load_sharded(path)
    # an uncommitted generation is invisible to discovery
    certify(_save(tmp_path, 2, "older.ckpt"))
    assert latest_certified(str(tmp_path)) == str(tmp_path / "older.ckpt")


def test_torn_shard_rejected(tmp_path):
    path = _save(tmp_path, 4)
    shard = os.path.join(path, cs.shard_file_name(0))
    raw = bytearray(open(shard, "rb").read())
    raw[-3] ^= 0xFF  # flip a byte inside the last entry's payload
    open(shard, "wb").write(bytes(raw))
    with pytest.raises(CheckpointCorruptionError, match="crc|CRC|corrupt"):
        cs.load_sharded(path)


def test_missing_shard_file_rejected(tmp_path):
    path = _save(tmp_path, 4)
    os.remove(os.path.join(path, cs.shard_file_name(0)))
    ok, why = cs.bootable(path)
    assert not ok and "shard" in why
    with pytest.raises(CheckpointCorruptionError, match="missing shard"):
        cs.load_sharded(path)


def test_partial_reads_are_window_sized(tmp_path):
    """Elastic restore seeks into shard files and reads single window entries:
    the bytes read equal the leaf payloads, not the shard-file sizes (headers,
    skeleton, and manifest ride outside the byte accounting)."""
    path = _save(tmp_path, 8)
    stats = {}
    cs.elastic_restore(path, lambda *a: None, stats=stats)
    want = _expect()
    payload = sum(a.nbytes for a in want.values())
    assert stats["bytes_read"] == payload
    shard_bytes = sum(
        os.path.getsize(os.path.join(path, f)) for f in os.listdir(path) if f.startswith("shard_")
    )
    assert shard_bytes > payload  # headers/index make the files strictly larger


def test_sweep_orphaned_gc(tmp_path):
    committed = _save(tmp_path, 2, "gen_2.ckpt")
    abandoned = _save(tmp_path, 2, "gen_1.ckpt")
    os.remove(os.path.join(abandoned, cs.COMMIT_NAME))
    old = os.path.getmtime(committed) - 60
    os.utime(abandoned, (old, old))
    # an orphaned commit marker: committed dir whose shards vanished out-of-band
    orphan = _save(tmp_path, 2, "gen_0.ckpt")
    os.remove(os.path.join(orphan, cs.shard_file_name(0)))
    swept = cs.sweep_orphaned(str(tmp_path))
    assert abandoned in swept and orphan in swept
    assert not os.path.exists(abandoned) and not os.path.exists(orphan)
    assert os.path.isdir(committed) and cs.is_committed(committed)


def test_certify_stamp_and_compat_gate(tmp_path):
    path = _save(tmp_path, 4)
    certify(path, policy_step=9)
    info = certified_info(path)
    assert info["format"] == "sharded"
    assert info["shard_format_version"] == cs.SHARD_FORMAT_VERSION
    # device_count stamps the saving RUNTIME world; the mesh facts ride separately
    assert info["topology"]["device_count"] == jax.device_count()
    assert info["topology"]["mesh_shape"] == [4]
    ok, _ = artifact_bootable(path, info)
    assert ok
    # a replica built before the sharded format must refuse to swap onto it
    ok, why = artifact_bootable(path, dict(info, format="sharded-v99"))
    assert not ok and "format" in why
    ok, why = artifact_bootable(path, dict(info, shard_format_version=cs.SHARD_FORMAT_VERSION + 1))
    assert not ok and "newer than this build" in why
    # legacy single-file artifacts keep their stamp and stay bootable
    legacy = str(tmp_path / "legacy.ckpt")
    save_state(legacy, {"x": np.ones((2,), np.float32)})
    certify(legacy)
    linfo = certified_info(legacy)
    assert linfo["format"] == "file-v1"
    ok, _ = artifact_bootable(legacy, linfo)
    assert ok


def test_lazy_pickle_peak_ram_and_roundtrip(tmp_path):
    """``save_state`` streams device leaves through the lazy host pickler: the
    transient host footprint is ~one leaf, not the whole tree (the old
    ``_to_host`` materialized every leaf before pickling began)."""
    import tracemalloc

    leaf_bytes = 4 << 20  # 4 MiB per leaf
    n_leaves = 4
    state = {
        f"p{i}": jax.device_put(np.full(leaf_bytes // 4, float(i), np.float32)) for i in range(n_leaves)
    }
    path = str(tmp_path / "big.ckpt")
    tracemalloc.start()
    tracemalloc.reset_peak()
    save_state(path, state)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < (n_leaves - 1) * leaf_bytes, f"peak {peak} suggests the whole tree was materialized"
    out = load_state(path)
    for i in range(n_leaves):
        np.testing.assert_array_equal(np.asarray(out[f"p{i}"]), np.full(leaf_bytes // 4, float(i), np.float32))


def test_callback_async_sharded_path(tmp_path):
    """The async callback path: the train thread pays only the snapshot;
    certification and GC land on the writer thread, keep_last windows apply to
    sharded DIRECTORIES, and the newest committed generation is discoverable."""
    ckpt = cs.ShardedCheckpointer(process_index=0, world=1)
    cb = CheckpointCallback(keep_last=2, checkpointer=ckpt)
    try:
        for i in range(4):
            state = {"w": jax.device_put(np.full((4, 4), float(i), np.float32)), "step": i}
            cb.on_checkpoint_coupled(None, str(tmp_path / f"ckpt_{i}.ckpt"), state, healthy=True, policy_step=i)
        cb.flush()
    finally:
        ckpt.close()
    latest = latest_certified(str(tmp_path))
    assert latest == str(tmp_path / "ckpt_3.ckpt")
    state = load_state(latest)
    np.testing.assert_array_equal(np.asarray(state["w"]), np.full((4, 4), 3.0, np.float32))
    survivors = sorted(d for d in os.listdir(str(tmp_path)) if d.endswith(".ckpt"))
    assert survivors == ["ckpt_2.ckpt", "ckpt_3.ckpt"]
