"""Real 2-process jax.distributed test over localhost (CPU backend).

Reference counterpart: the reference proves its distributed path with CPU-Gloo
multi-process launches (tests/test_algos/test_algos.py `devices` fixture); here two
subprocesses form a jax.distributed world and the test asserts log-dir broadcast,
DP gradient agreement, and checkpoint write-once (VERDICT r1 item 4).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_CHILD = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.timeout(300)
def test_two_process_distributed(tmp_path):
    port, nproc = _free_port(), 2
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, _CHILD, str(port), str(pid), str(nproc), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(nproc)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"child failed:\n--- stdout ---\n{out}\n--- stderr ---\n{err}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    by_pid = {o["pid"]: o for o in outs}
    assert set(by_pid) == {0, 1}

    # rank-0's versioned log dir reached every process
    assert by_pid[0]["log_dir"] == by_pid[1]["log_dir"]
    assert "version_0" in by_pid[0]["log_dir"]

    # DP gradients agree bit-for-bit across processes (XLA allreduce), and they are
    # nonzero (i.e. the comparison is not trivially 0 == 0)
    g0, g1 = np.asarray(by_pid[0]["grad"]), np.asarray(by_pid[1]["grad"])
    np.testing.assert_array_equal(g0, g1)
    assert np.abs(g0).sum() > 0

    # checkpoint written exactly once (global-zero only), visible to both
    assert by_pid[0]["ckpt_exists"] and by_pid[1]["ckpt_exists"]
    ckpts = [f for f in os.listdir(tmp_path) if f.startswith("ckpt_")]
    assert len(ckpts) == 1
