"""Real 2-process jax.distributed test over localhost (CPU backend).

Reference counterpart: the reference proves its distributed path with CPU-Gloo
multi-process launches (tests/test_algos/test_algos.py `devices` fixture); here two
subprocesses form a jax.distributed world and the test asserts log-dir broadcast,
DP gradient agreement, and checkpoint write-once (VERDICT r1 item 4).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_CHILD = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.timeout(300)
def test_two_process_distributed(tmp_path):
    by_pid, workdir = _run_children(_free_port(), 2, tmp_path)
    assert set(by_pid) == {0, 1}

    # rank-0's versioned log dir reached every process
    assert by_pid[0]["log_dir"] == by_pid[1]["log_dir"]
    assert "version_0" in by_pid[0]["log_dir"]

    # DP gradients agree bit-for-bit across processes (XLA allreduce), and they are
    # nonzero (i.e. the comparison is not trivially 0 == 0)
    g0, g1 = np.asarray(by_pid[0]["grad"]), np.asarray(by_pid[1]["grad"])
    np.testing.assert_array_equal(g0, g1)
    assert np.abs(g0).sum() > 0

    # checkpoint written exactly once (global-zero only), visible to both
    assert by_pid[0]["ckpt_exists"] and by_pid[1]["ckpt_exists"]
    ckpts = [f for f in os.listdir(workdir) if f.startswith("ckpt_")]
    assert len(ckpts) == 1


# XLA's CPU-Gloo collective runtime occasionally aborts a rank mid-collective
# (``gloo::EnforceNotMet ... op.preamble.length <= op.nbytes``) or wedges the
# world when concurrent collectives race on one TCP pair; the peers then die on
# the coordination-service fatal. The race lives in jaxlib's C++ runtime (it
# reproduces at every commit of this repo, CPU backend only) — so a world whose
# failure matches these signatures is retried on a fresh port + workdir, while
# a rank that fails for any other reason (assertion, traceback, bad exit) still
# fails the test on the first attempt.
_INFRA_RACE_SIGNATURES = (
    "gloo::EnforceNotMet",
    "Gloo all-reduce failed",
    "JAX distributed service detected fatal errors",
    "Connection reset by peer",
    "heartbeat timeout",
)


def _spawn_world(port, nproc, workdir, mode, extra_args, timeout, child):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, child, str(port), str(pid), str(nproc), str(workdir)]
            + ([mode] if mode else [])
            + (extra_args[pid] if extra_args else []),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(nproc)
    ]
    results, timed_out = [], False
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            timed_out = True
            for q in procs:
                q.kill()
            out, err = p.communicate()
        results.append((p, out, err))
    return results, timed_out


def _run_children(port, nproc, tmp_path, mode=None, extra_args=None, timeout=240, child=_CHILD, attempts=3):
    per_attempt = max(120, timeout // attempts)
    last_report = ""
    for attempt in range(attempts):
        # fresh workdir per attempt: a crashed world may leave partial run dirs
        # and checkpoints behind, which would corrupt version-numbering and
        # write-once assertions on the retry
        workdir = os.path.join(str(tmp_path), f"attempt{attempt}")
        os.makedirs(workdir, exist_ok=True)
        world_port = port if attempt == 0 else _free_port()
        results, timed_out = _spawn_world(
            world_port, nproc, workdir, mode, extra_args, per_attempt, child
        )
        # report every rank, not just the first nonzero one: when one rank dies
        # its peers abort on the coordination fatal, and the peer's stderr only
        # ever says "another task died" — the root cause is in the rank that
        # exited first
        report = "\n".join(
            f"--- rank {i} rc={p.returncode} stdout ---\n{out}\n--- rank {i} stderr ---\n{err}"
            for i, (p, out, err) in enumerate(results)
        )
        if not timed_out and all(p.returncode == 0 for p, _, _ in results):
            outs = [json.loads(out.strip().splitlines()[-1]) for _, out, _ in results]
            return {o["pid"]: o for o in outs}, workdir
        kind = "world timed out" if timed_out else "child failed"
        last_report = f"{kind} (attempt {attempt + 1}/{attempts}):\n{report}"
        if not (timed_out or any(sig in report for sig in _INFRA_RACE_SIGNATURES)):
            break
        print(
            f"[multihost] transient collective-runtime failure, retrying on a fresh world\n{last_report}",
            file=sys.stderr,
        )
    pytest.fail(last_report)


@pytest.mark.timeout(120)
def test_coordinator_absent_times_out_fast(tmp_path):
    """No coordinator listening: the process must fail within the configured
    multihost_timeout_s instead of hanging for jax's 300 s default.

    jax's coordination client aborts the process fatally (absl F-log) on a
    registration deadline rather than raising a catchable exception, so 'fast,
    loud death' IS the detectable failure mode; Runtime's multihost_timeout_s
    is what bounds it."""
    import time

    port = _free_port()  # nobody binds it: process_id=1 waits for a coordinator that never comes
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    t0 = time.monotonic()
    p = subprocess.Popen(
        [sys.executable, _CHILD, str(port), "1", "2", str(tmp_path), "timeout"],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    out, err = p.communicate(timeout=90)
    elapsed = time.monotonic() - t0
    if p.returncode == 0:  # future jax: initialize raises cleanly and Runtime wraps it
        res = json.loads(out.strip().splitlines()[-1])
        assert res["raised"], "Runtime must raise when the coordinator is absent"
        assert "multihost" in res["msg"]
    else:
        assert "DEADLINE_EXCEEDED" in err or "Deadline Exceeded" in err, f"unexpected failure:\n{err}"
    assert elapsed < 60, f"coordinator-absent boot took {elapsed:.0f}s — timeout not applied"


@pytest.mark.timeout(300)
def test_mismatched_device_counts_rejected(tmp_path):
    """Processes with different local device counts must fail fast with a clear
    error (DP meshes need equal per-rank shards), not die later in sharding."""
    by_pid, _ = _run_children(
        _free_port(), 2, tmp_path, "mismatch", extra_args={0: ["2"], 1: ["4"]}
    )
    for pid in (0, 1):
        assert by_pid[pid]["raised"], f"process {pid} accepted a heterogeneous pod"
        assert "Heterogeneous local device counts" in by_pid[pid]["msg"]


@pytest.mark.timeout(600)
def test_crosshost_decoupled_ppo_step(tmp_path):
    """A full decoupled PPO round across 2 processes: global device 0 plays,
    the other 3 devices form the cross-process trainer mesh. Asserts the real
    jitted PPO optimization ran (params changed), stayed bit-identical across
    processes (the XLA allreduce), and the player refresh matches exactly."""
    child = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "decoupled_child.py")
    by_pid, _ = _run_children(_free_port(), 2, tmp_path, timeout=540, child=child)
    for pid in (0, 1):
        assert by_pid[pid]["changed"], "optimization must actually update params"
        assert by_pid[pid]["player_matches"]
    assert by_pid[0]["head"] == by_pid[1]["head"], "post-update params must agree bit-for-bit"
    assert by_pid[0]["digest"] == by_pid[1]["digest"]
    assert "id=0" in by_pid[0]["player_device"]  # refresh landed on the player chip


@pytest.mark.timeout(600)
def test_crosshost_decoupled_ppo_cli(tmp_path):
    """The reference's flagship distributed mode through the REAL CLI: a
    2-process `exp=ppo_decoupled fabric.multihost=True` launch must train
    end-to-end over the cross-process trainer mesh and write the final
    checkpoint (reference multi-node launch, ppo_decoupled.py:623-670)."""
    child = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "decoupled_cli_child.py")
    by_pid, _ = _run_children(_free_port(), 2, tmp_path, "ppo_decoupled", timeout=540, child=child)
    for pid in (0, 1):
        assert by_pid[pid]["done"]
    assert by_pid[0]["n_ckpts"] >= 1, "the player process must write the final checkpoint"


@pytest.mark.timeout(600)
def test_crosshost_decoupled_sac_cli(tmp_path):
    """Same as above for `exp=sac_decoupled`: player owns the replay buffer and
    samples, trainer processes join on spec-shaped zero templates (reference
    sac_decoupled.py:548-588)."""
    child = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "decoupled_cli_child.py")
    by_pid, _ = _run_children(_free_port(), 2, tmp_path, "sac_decoupled", timeout=540, child=child)
    for pid in (0, 1):
        assert by_pid[pid]["done"]
    assert by_pid[0]["n_ckpts"] >= 1, "the player process must write the final checkpoint"


@pytest.mark.timeout(300)
def test_resume_under_multihost(tmp_path):
    """Write-once checkpoint -> every process reloads identical state, and the
    resumed run's log dir version-bumps consistently on all processes."""
    by_pid, _ = _run_children(_free_port(), 2, tmp_path, "resume")
    for pid in (0, 1):
        assert by_pid[pid]["iter_num"] == 123
        np.testing.assert_array_equal(
            np.asarray(by_pid[pid]["loaded"]), np.asarray(by_pid[pid]["expected"])
        )
        assert "version_0" in by_pid[pid]["log_dir_1"]
        assert "version_1" in by_pid[pid]["log_dir_2"]
    assert by_pid[0]["log_dir_2"] == by_pid[1]["log_dir_2"]
