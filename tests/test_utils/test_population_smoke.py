"""Satellite registration of scripts/population_smoke.py as a tier-1 test: the
fleet chaos drill — a two-trial population on preemptible slots must survive a
controller kill-and-restart plus two injected slot preemptions, resow the
ChaosEnv-diverged trial from the clean peer's certified checkpoint with
perturbed hyperparameters, and finish with every trial completed, the resow
edge in lineage.jsonl, and zero orphaned trial subprocesses (full harness,
fresh interpreters all the way down)."""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.faults
@pytest.mark.timeout(780)
def test_population_smoke_fleet_chaos_drill(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "population_smoke.py"),
            "--workdir",
            str(tmp_path),
            "--timeout",
            "660",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=740,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-2500:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "population smoke OK" in out.stdout
    # the drill's own assertions already ran; independently re-check the two
    # fleet-level artifacts it leaves behind
    with open(tmp_path / "orchestrate" / "lineage.jsonl") as f:
        edges = [json.loads(line) for line in f if line.strip()]
    resows = [e for e in edges if e["kind"] == "resow" and e.get("parent") == "a_clean"]
    assert resows, [e["kind"] for e in edges]
    assert os.path.exists(resows[0]["ckpt"] + ".certified.json"), resows[0]
    with open(tmp_path / "orchestrate" / "journal.json") as f:
        journal = json.load(f)
    assert {t["spec"]["key"]: t["state"] for t in journal["trials"]} == {
        "a_clean": "completed",
        "b_chaos": "completed",
    }
    assert journal["counters"]["injections"] >= 2
    assert journal["counters"]["controller_incarnations"] >= 2
