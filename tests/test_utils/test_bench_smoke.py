"""bench.py --smoke: the in-process harness check the suite actually runs.

The real bench targets need the accelerator tunnel; the smoke mode is the one
path that keeps the harness from bit-rotting unnoticed, so it is pinned here
as a plain (non-slow) test — covering BOTH on-policy buffer backends.
"""

import json
import subprocess
import sys

import pytest

import bench


def test_bench_smoke_runs_both_backends(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    result = bench.bench_smoke(total_steps=64)
    assert result["smoke"] is True
    assert result["metric"] == "ppo_smoke_env_steps_per_sec"
    for backend in ("host", "device"):
        rate = result[f"smoke_{backend}_env_steps_per_sec"]
        assert rate > 0, f"{backend} backend produced a non-positive rate"
    assert result["value"] == result["smoke_host_env_steps_per_sec"]
    json.dumps(result)  # the bench contract: one JSON-serializable dict


def test_target_metric_names():
    assert bench._target_metric("ppo") == "ppo_cartpole_env_steps_per_sec"
    assert bench._target_metric("dv3") == "dv3_gsteps_per_sec"
    assert bench._target_metric("smoke") == "ppo_smoke_env_steps_per_sec"
    assert bench._target_metric("all") == "ppo_cartpole_env_steps_per_sec"
    with pytest.raises(KeyError):
        bench._target_metric("nope")


@pytest.mark.slow
def test_bench_smoke_cli_emits_one_json_line(tmp_path):
    """End-to-end stdout contract: `python bench.py --smoke` prints EXACTLY one
    line on stdout and it is the result JSON (driver parses stdout verbatim)."""
    out = subprocess.run(
        [sys.executable, str(bench.__file__), "--smoke"],
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=600,
        check=True,
    )
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"bench --smoke stdout must be one JSON line, got: {lines}"
    result = json.loads(lines[0])
    assert result["smoke"] is True and result["value"] > 0
