"""MLflow logger + model-manager backend (skipped when mlflow is not installed).

Reference: sheeprl/utils/logger.py:12-36 (MLFlowLogger selection) and
sheeprl/utils/mlflow.py:73-295 (MlflowModelManager) — exercised against mlflow's
local file store.
"""

import os
import pickle

import numpy as np
import pytest

from sheeprl_tpu.config import compose
from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE


def test_mlflow_logger_config_selectable():
    cfg = compose(config_name="config", overrides=["exp=ppo", "logger@metric.logger=mlflow"])
    assert cfg.metric.logger._target_ == "sheeprl_tpu.utils.logger.MLflowLogger"
    assert cfg.metric.logger.experiment_name == "ppo_CartPole-v1"
    # the default selection is untouched
    cfg2 = compose(config_name="config", overrides=["exp=ppo"])
    assert cfg2.metric.logger._target_ == "sheeprl_tpu.utils.logger.TensorBoardLogger"


def test_mlflow_logger_raises_without_mlflow():
    if _IS_MLFLOW_AVAILABLE:
        pytest.skip("mlflow installed: the import guard is exercised by the real tests below")
    from sheeprl_tpu.utils.logger import MLflowLogger

    with pytest.raises(ModuleNotFoundError, match="mlflow"):
        MLflowLogger(experiment_name="x", tracking_uri="file:///tmp/none")


@pytest.mark.skipif(not _IS_MLFLOW_AVAILABLE, reason="mlflow not installed")
def test_mlflow_logger_file_store(tmp_path):
    from sheeprl_tpu.utils.logger import MLflowLogger

    uri = f"file://{tmp_path}/mlruns"
    logger = MLflowLogger(experiment_name="exp", tracking_uri=uri, run_name="run")
    logger.log_metrics({"Loss/a": 1.5, "Rewards/rew_avg": 2.0}, step=3)
    logger.log_hyperparams({"algo": {"name": "ppo", "lr": 1e-3}})
    logger.finalize()

    from mlflow.tracking import MlflowClient

    client = MlflowClient(tracking_uri=uri)
    run = client.get_run(logger.run_id)
    assert run.data.metrics["Loss_a"] == 1.5
    assert run.data.params["algo.name"] == "ppo"
    assert run.info.status == "FINISHED"


@pytest.mark.skipif(not _IS_MLFLOW_AVAILABLE, reason="mlflow not installed")
def test_mlflow_model_manager_roundtrip(tmp_path, monkeypatch):
    from sheeprl_tpu.utils.model_manager import MlflowModelManager

    monkeypatch.setenv("MLFLOW_TRACKING_URI", f"file://{tmp_path}/mlruns")
    mm = MlflowModelManager(None)

    payload = {"w": np.arange(4, dtype=np.float32)}
    art = tmp_path / "agent.pkl"
    with open(art, "wb") as f:
        pickle.dump(payload, f)

    v1 = mm.register_model(str(art), "agent", description="first")
    assert v1.version == 1
    v2 = mm.register_model(str(art), "agent")
    assert v2.version == 2
    assert mm.get_latest_version("agent").version == 2

    # registration must have UPLOADED the bytes: callers delete the local artifact
    # right after registering (register_model_from_checkpoint's temp-dir cleanup)
    os.remove(art)

    mm.transition_model("agent", 2, "Staging")
    assert mm.get_latest_version("agent").stage == "Staging"

    out = tmp_path / "dl"
    mm.download_model("agent", 2, str(out))
    assert len(os.listdir(out)) == 1

    loaded = mm.load_model("agent")
    np.testing.assert_array_equal(loaded["w"], payload["w"])

    mm.delete_model("agent", 1)
    assert mm.get_latest_version("agent").version == 2


def test_package_scoped_selection_does_not_leak():
    from sheeprl_tpu.config.loader import ConfigError

    # the package-scoped override targets metric.logger only; an unknown group errors
    with pytest.raises(ConfigError, match="unknown config group"):
        compose(config_name="config", overrides=["exp=ppo", "nosuchgroup@metric.logger=mlflow"])


def test_tensorboard_sidecar_lands_in_versioned_run_dir(tmp_path, monkeypatch):
    """get_log_dir wires the version_N dir into the active logger, so the
    metrics.json ranking sidecar sits next to the run's checkpoints."""
    monkeypatch.chdir(tmp_path)
    from sheeprl_tpu.utils import logger as logger_mod

    cfg = compose(config_name="config", overrides=["exp=ppo", "metric.log_level=1"])
    lg = logger_mod.get_logger(None, cfg)
    run_dir = logger_mod.get_log_dir(None, "algo", "run", logger=lg)
    assert run_dir.endswith("version_0")
    lg.log_metrics({"Test/cumulative_reward": 7.0}, step=1)
    lg.finalize()
    with open(os.path.join(run_dir, "metrics.json")) as f:
        import json

        assert json.load(f)["Test/cumulative_reward"] == 7.0


def test_package_typo_rejected():
    from sheeprl_tpu.config.loader import ConfigError

    with pytest.raises(ConfigError, match="matched no mount"):
        compose(config_name="config", overrides=["exp=ppo", "logger@metric.loger=mlflow"])


def test_root_mount_package_override():
    # Hydra-valid spelling addressing a root mount's own package
    cfg = compose(config_name="config", overrides=["exp=ppo", "algo@algo=a2c"])
    assert cfg.algo.name == "a2c"
