"""CrossHostTransport payload-spec exchange: unit-level (the 2-process
integration runs live in test_multihost.py; here the coordinator KV store is
faked so the caching/template semantics are pinned cheaply)."""

import numpy as np
import pytest

import sheeprl_tpu.parallel.decoupled as decoupled_mod
from sheeprl_tpu.parallel.decoupled import CrossHostTransport


class _FakeKV:
    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        self.store[key] = value

    def blocking_key_value_get(self, key, timeout_ms):
        if key not in self.store:
            raise TimeoutError(f"no value for {key}")
        return self.store[key]


@pytest.fixture()
def transport_pair(monkeypatch):
    kv = _FakeKV()
    monkeypatch.setattr(decoupled_mod, "_kv_client", lambda: kv)
    player = CrossHostTransport.__new__(CrossHostTransport)
    trainer = CrossHostTransport.__new__(CrossHostTransport)
    for t, is_player in ((player, True), (trainer, False)):
        t.is_player_process = is_player
        t._specs = {}
        t._zero_payloads = {}
        t._scope = ""
    return player, trainer, kv


def test_spec_roundtrip_and_zero_templates(transport_pair):
    player, trainer, _ = transport_pair
    payload = {
        "obs": np.zeros((4, 3, 5), np.float32),
        "rew": np.zeros((4, 3, 1), np.float32),
        "pix": np.zeros((4, 3, 2, 2), np.uint8),
    }
    spec = player.sync_payload_spec("roll", payload)
    got = trainer.sync_payload_spec("roll")
    assert got == spec
    assert got["pix"] == ((4, 3, 2, 2), "uint8")

    tpl = trainer.zeros_payload("roll")
    assert set(tpl) == set(payload)
    assert tpl["obs"].shape == (4, 3, 5) and tpl["obs"].dtype == np.float32
    # the dict is a fresh shallow copy each call (callers pop keys), the arrays cached
    tpl.pop("obs")
    tpl2 = trainer.zeros_payload("roll")
    assert "obs" in tpl2
    assert tpl2["rew"] is trainer.zeros_payload("roll")["rew"]


def test_spec_is_cached_after_first_exchange(transport_pair):
    player, trainer, kv = transport_pair
    player.sync_payload_spec("t", {"a": np.zeros((2,), np.float32)})
    trainer.sync_payload_spec("t")
    kv.store.clear()  # later calls must not touch the store again
    assert player.sync_payload_spec("t")["a"] == ((2,), "float32")
    assert trainer.sync_payload_spec("t")["a"] == ((2,), "float32")


def test_scope_isolates_runs(transport_pair):
    player, trainer, _ = transport_pair
    player.set_scope("logs/run_A")
    trainer.set_scope("logs/run_B")
    player.sync_payload_spec("roll", {"a": np.zeros((2,), np.float32)})
    # different scope -> the stale run-A spec must NOT satisfy run B; the
    # exhausted deadline surfaces as the diagnostic transport error
    with pytest.raises(decoupled_mod.TransportTimeoutError):
        trainer.sync_payload_spec("roll")
    trainer.set_scope("logs/run_A")
    assert trainer.sync_payload_spec("roll")["a"] == ((2,), "float32")


def test_player_must_provide_payload(transport_pair):
    player, _, _ = transport_pair
    with pytest.raises(ValueError, match="must provide the payload"):
        player.sync_payload_spec("empty")


def test_resume_digest_match_and_mismatch(transport_pair, tmp_path):
    """Process 0 publishes its checkpoint digest; a trainer process with the
    same file passes, one with a divergent copy fails fast (advisor r4)."""
    player, trainer, _ = transport_pair
    ckpt = tmp_path / "ckpt_1_0.ckpt"
    ckpt.write_bytes(b"same-bytes" * 1000)

    player.verify_resume_digest(str(ckpt))
    trainer.verify_resume_digest(str(ckpt))  # identical copy: no raise

    stale = tmp_path / "stale.ckpt"
    stale.write_bytes(b"other-bytes" * 1000)
    with pytest.raises(RuntimeError, match="Resume checkpoint mismatch"):
        trainer.verify_resume_digest(str(stale))


def test_resume_digest_scoped_per_run(transport_pair, tmp_path):
    """Digests ride the same run-scoped keys as the payload specs."""
    player, trainer, kv = transport_pair
    ckpt = tmp_path / "c.ckpt"
    ckpt.write_bytes(b"x" * 64)
    player.set_scope("logs/runs/a/version_0")
    player.verify_resume_digest(str(ckpt))
    trainer.set_scope("logs/runs/a/version_1")  # different incarnation
    with pytest.raises(decoupled_mod.TransportTimeoutError):
        trainer.verify_resume_digest(str(ckpt))


def test_ckpt_digest_sees_mid_file_divergence(tmp_path):
    """Two same-size checkpoints with identical head/tail bookkeeping but
    different params mid-stream must digest differently (the middle chunk);
    with a 4 KiB chunk the 3x4KiB samples never reach the middle of 64 KiB."""
    chunk = 4 * 1024
    size = 64 * 1024
    base = bytearray(size)
    a = tmp_path / "a.ckpt"
    a.write_bytes(bytes(base))
    diverged = bytearray(base)
    diverged[size // 2] = 0xFF  # outside head [0, 4K) and tail [60K, 64K)
    b = tmp_path / "b.ckpt"
    b.write_bytes(bytes(diverged))

    da = decoupled_mod._ckpt_digest(str(a), chunk=chunk)
    db = decoupled_mod._ckpt_digest(str(b), chunk=chunk)
    assert da != db
    assert da.startswith(f"{size}:") and db.startswith(f"{size}:")


def test_ckpt_digest_small_and_boundary_files(tmp_path):
    """Files at/below one or two chunks stay well-defined and content-sensitive."""
    chunk = 1024
    for size in (0, 1, chunk, chunk + 1, 2 * chunk, 2 * chunk + 1, 3 * chunk):
        p = tmp_path / f"f_{size}.ckpt"
        p.write_bytes(b"\x01" * size)
        d1 = decoupled_mod._ckpt_digest(str(p), chunk=chunk)
        assert d1.startswith(f"{size}:")
        if size:
            q = tmp_path / f"g_{size}.ckpt"
            q.write_bytes(b"\x01" * (size - 1) + b"\x02")
            assert decoupled_mod._ckpt_digest(str(q), chunk=chunk) != d1
