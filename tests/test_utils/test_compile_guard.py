"""Retrace guard + AOT routing unit tests (core/compile.py).

Covers the perf contract the train loops rely on: a warmed signature never
traces, a drifting signature is counted and diffed, and ``guard.policy=halt``
turns post-steady drift into a hard error instead of a silent recompile storm.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.core import compile as jax_compile


@pytest.fixture(autouse=True)
def _reset_guard_state():
    # policy/steady watermark are process-wide: restore the defaults so test
    # order never leaks a `halt` policy into unrelated tests
    jax_compile.configure({})
    yield
    jax_compile.configure({})


def test_first_compile_is_not_a_retrace():
    gfn = jax_compile.guarded_jit(lambda x: x * 2, name="t.first")
    out = gfn(jnp.ones((4,)))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    assert gfn.traces == 1
    assert gfn.retraces == 0


def test_shape_drift_counts_retraces_and_logs_diff(caplog):
    gfn = jax_compile.guarded_jit(lambda x: x + 1, name="t.drift")
    gfn(jnp.ones((4,)))
    with caplog.at_level(logging.WARNING, logger="sheeprl_tpu.compile"):
        gfn(jnp.ones((8,)))
    assert gfn.retraces == 1
    assert gfn.last_diff is not None
    assert "(4,)" in gfn.last_diff and "(8,)" in gfn.last_diff
    assert any("retrace" in rec.message for rec in caplog.records)
    # same shapes again: served from jit's cache, no new trace
    calls_before = gfn.traces
    gfn(jnp.ones((8,)))
    assert gfn.traces == calls_before


def test_dtype_drift_is_diffed():
    gfn = jax_compile.guarded_jit(lambda x: x + 1, name="t.dtype")
    gfn(jnp.ones((4,), jnp.float32))
    gfn(jnp.ones((4,), jnp.int32))
    assert gfn.retraces == 1
    assert "float32" in gfn.last_diff and "int32" in gfn.last_diff


def test_halt_policy_raises_after_steady():
    jax_compile.configure({"compile": {"guard": {"policy": "halt"}}})
    gfn = jax_compile.guarded_jit(lambda x: x * 3, name="t.halt")
    gfn(jnp.ones((4,)))
    jax_compile.mark_steady()
    with pytest.raises(jax_compile.RetraceError):
        gfn(jnp.ones((16,)))


def test_warn_policy_never_raises_after_steady():
    gfn = jax_compile.guarded_jit(lambda x: x * 3, name="t.warn")
    gfn(jnp.ones((4,)))
    jax_compile.mark_steady()
    gfn(jnp.ones((16,)))  # logs, but must not raise
    assert gfn.retraces == 1


def test_aot_route_never_traces():
    gfn = jax_compile.guarded_jit(lambda x: x @ x, name="t.aot")
    gfn.aot_compile(jax.ShapeDtypeStruct((3, 3), jnp.float32))
    assert gfn.aot_compiles == 1
    out = gfn(jnp.eye(3))
    np.testing.assert_allclose(np.asarray(out), np.eye(3))
    assert gfn.traces == 0
    assert gfn.calls == 1


def test_aot_route_accepts_weak_typed_inputs():
    # jnp.full with a python float builds a weak-typed array; the router must
    # still hit the strong-typed executable (weak_type is erased from the key)
    gfn = jax_compile.guarded_jit(lambda x: x + x, name="t.weak")
    gfn.aot_compile(jax.ShapeDtypeStruct((3, 3), jnp.float32))
    gfn(jnp.full((3, 3), 2.0))
    assert gfn.traces == 0


def test_unwarmed_shape_falls_back_to_jit_and_counts_retrace():
    gfn = jax_compile.guarded_jit(lambda x: x + 1, name="t.fallback")
    gfn.aot_compile(jax.ShapeDtypeStruct((4,), jnp.float32))
    # a shape the warmup did not cover: correctness first (jit path), but the
    # guard flags it — this is exactly the drift the AOT specs must prevent
    out = gfn(jnp.ones((5,)))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    assert gfn.traces == 1
    assert gfn.retraces == 1


def test_guarded_jit_static_argnames():
    def f(x, flag):
        return x * 2 if flag else x

    gfn = jax_compile.guarded_jit(f, name="t.static", static_argnames=("flag",))
    np.testing.assert_allclose(np.asarray(gfn(jnp.ones(()), True)), 2.0)
    np.testing.assert_allclose(np.asarray(gfn(jnp.ones(()), False)), 1.0)
    assert gfn.traces == 2  # one per static value: expected, both are first compiles per branch


def test_drain_compile_counters_reports_delta():
    gfn = jax_compile.guarded_jit(lambda x: x + 1, name="t.drain")
    gfn(jnp.ones((2,)))
    gfn(jnp.ones((3,)))
    jax_compile.drain_compile_counters(None)  # snapshot
    delta = jax_compile.drain_compile_counters(None)
    assert delta["Compile/retraces"] == 0.0
    gfn(jnp.ones((7,)))
    delta = jax_compile.drain_compile_counters(None)
    assert delta["Compile/retraces"] == 1.0


def test_signature_excludes_committed_device_but_keeps_structure():
    gfn = jax_compile.guarded_jit(lambda tree: tree["a"] + tree["b"], name="t.tree")
    gfn.aot_compile({"a": jax.ShapeDtypeStruct((2,), jnp.float32), "b": jax.ShapeDtypeStruct((2,), jnp.float32)})
    gfn({"a": jnp.ones((2,)), "b": jnp.ones((2,))})
    assert gfn.traces == 0
    # different pytree structure: distinct signature, routed to the jit path
    gfn({"a": jnp.ones((2,)), "b": jnp.ones((2,)), "c": jnp.ones((2,))})


def test_pow2_bucket():
    assert jax_compile.pow2_bucket(0) == 1
    assert jax_compile.pow2_bucket(1) == 1
    assert jax_compile.pow2_bucket(3) == 4
    assert jax_compile.pow2_bucket(4) == 4
    assert jax_compile.pow2_bucket(9) == 16
    assert jax_compile.pow2_bucket(2, minimum=8) == 8


def test_bucketed_pad_shapes_and_mask():
    chunks = {
        "obs": [np.ones((3, 5), np.float32), np.ones((2, 5), np.float32), np.ones((4, 5), np.float32)],
        "rew": [np.ones((3, 1), np.float32), np.ones((2, 1), np.float32), np.ones((4, 1), np.float32)],
    }
    out = jax_compile.bucketed_pad(chunks, lengths=[3, 2, 4], length=4)
    assert out["obs"].shape == (4, 4, 5)  # [sl, pow2_bucket(3)=4, feat]
    assert out["rew"].shape == (4, 4, 1)
    assert out["mask"].shape == (4, 4, 1)
    np.testing.assert_array_equal(out["mask"][:, 0, 0], [1, 1, 1, 0])
    np.testing.assert_array_equal(out["mask"][:, 1, 0], [1, 1, 0, 0])
    np.testing.assert_array_equal(out["mask"][:, 3, 0], [0, 0, 0, 0])  # pure padding column
    assert out["obs"][3, 1].sum() == 0.0  # padded rows stay zero


def test_bucketed_pad_rejects_empty_and_ragged():
    with pytest.raises(ValueError):
        jax_compile.bucketed_pad({"x": []}, lengths=[], length=4)
    with pytest.raises(ValueError):
        jax_compile.bucketed_pad({"x": [np.ones((2, 1))]}, lengths=[2, 3], length=4)
