"""Satellite registration of scripts/compile_smoke.py as a tier-1 test: two
fresh-interpreter runs against one temporary persistent compilation cache must
show the warm run compiling strictly less (misses drop, hits appear) with zero
retraces — the on-disk half of the compile subsystem, which the in-process
tests cannot cover."""

import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.timeout(600)
def test_compile_smoke_cold_then_warm(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "compile_smoke.py"),
            "--workdir",
            str(tmp_path),
            "--timeout",
            "240",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-1500:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "compile smoke OK" in out.stdout
    # the harness's own assertions already ran; re-check the artifact exists
    assert os.listdir(tmp_path / "xla_cache"), "no persistent cache entries left on disk"
