"""Local model-registry tests (reference mlflow-backed manager, sheeprl/utils/mlflow.py)."""

import os
import pickle

import numpy as np
import pytest

from sheeprl_tpu.utils.model_manager import LocalModelManager, ModelInfo, log_model


class _FakeRuntime:
    log_dir = None

    def print(self, *a, **k):
        pass


@pytest.fixture()
def manager(tmp_path):
    return LocalModelManager(_FakeRuntime(), str(tmp_path / "registry"))


def _artifact(tmp_path, name="m.pkl"):
    path = tmp_path / name
    with open(path, "wb") as f:
        pickle.dump({"w": np.ones((2, 2))}, f)
    return str(path)


def test_register_and_versioning(manager, tmp_path):
    art = _artifact(tmp_path)
    v1 = manager.register_model(art, "ppo_agent", description="first")
    v2 = manager.register_model(art, "ppo_agent", description="second")
    assert (v1.version, v2.version) == (1, 2)
    latest = manager.get_latest_version("ppo_agent")
    assert latest.version == 2
    assert latest.description == "second"
    changelog = open(os.path.join(manager.registry_dir, "ppo_agent", "CHANGELOG.md")).read()
    assert "Version 1" in changelog and "Version 2" in changelog


def test_transition_and_delete(manager, tmp_path):
    art = _artifact(tmp_path)
    manager.register_model(art, "m")
    manager.register_model(art, "m")
    moved = manager.transition_model("m", 1, "production")
    assert moved.stage == "production"
    manager.delete_model("m", 2)
    assert manager.get_latest_version("m").version == 1
    with pytest.raises(ValueError):
        manager.delete_model("m", 2)


def test_download_and_load(manager, tmp_path):
    art = _artifact(tmp_path)
    manager.register_model(art, "m")
    out = tmp_path / "downloaded"
    manager.download_model("m", 1, str(out))
    assert (out / "model.pkl").is_file()
    tree = manager.load_model("m")
    assert np.allclose(tree["w"], 1.0)


def test_log_model_returns_uri(tmp_path):
    class _Cfg:
        class algo:
            name = "ppo"

        class env:
            id = "dummy"

    info = log_model(_FakeRuntime(), _Cfg, "agent", {"w": np.zeros(3)}, artifacts_dir=str(tmp_path / "arts"))
    assert isinstance(info, ModelInfo)
    assert os.path.isfile(info.model_uri)
    assert info._model_uri == info.model_uri


def test_registration_cli_from_ppo_checkpoint(standard_args, tmp_path, monkeypatch):
    """End-to-end: train PPO with a checkpoint, register its agent via the CLI."""
    from sheeprl_tpu.cli import registration, run

    monkeypatch.chdir(tmp_path)
    run(
        overrides=standard_args
        + [
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "fabric.devices=1",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=2",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "buffer.memmap=False",
            "env.num_envs=1",
            "checkpoint.save_last=True",
        ]
    )
    ckpts = []
    for root, _, files in os.walk(tmp_path / "logs"):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    assert len(ckpts) >= 1

    registry = tmp_path / "registry"
    registration(
        overrides=[f"checkpoint_path={ckpts[0]}", f"model_manager.registry_dir={registry}"]
    )
    model_dirs = os.listdir(registry)
    assert len(model_dirs) == 1  # PPO registers a single 'agent' model
    assert (registry / model_dirs[0] / "v1" / "model.pkl").is_file()


def test_register_best_models(manager, tmp_path):
    """Runs are ranked by metrics.json; the winner's checkpoint supplies the models."""
    import json

    exp = tmp_path / "exp"
    for name, score in [("run_a", 1.0), ("run_b", 5.0)]:
        run = exp / name / "version_0"
        (run / "checkpoint").mkdir(parents=True)
        with open(run / "metrics.json", "w") as f:
            json.dump({"Test/cumulative_reward": score}, f)
        with open(run / "checkpoint" / "ckpt_1_0.ckpt", "wb") as f:
            pickle.dump({"agent": {"w": np.full((2,), score)}, "iter_num": 1}, f)

    out = manager.register_best_models(str(exp), {"agent"})
    assert set(out) == {"agent"}
    tree = manager.load_model("agent")
    assert np.allclose(tree["w"], 5.0)  # run_b won
    assert "Best Test/cumulative_reward: 5.0" in manager.get_latest_version("agent").description


def test_register_best_models_real_run_layout(manager, tmp_path):
    """The real logger layout: a metrics.json COPY in the writer dir (parent,
    no checkpoint sibling) plus the versioned run dir holding a v1-container
    checkpoint — ranking must pick the root that owns the checkpoints, and the
    loader must decode the versioned envelope (both regressions caught by
    examples/model_manager.py)."""
    import json

    from sheeprl_tpu.utils.checkpoint import save_state

    exp = tmp_path / "exp" / "2026-01-01_ppo_42"
    run = exp / "version_0"
    (run / "checkpoint").mkdir(parents=True)
    metrics = {"Test/cumulative_reward": 7.0}
    with open(exp / "metrics.json", "w") as f:  # writer-dir copy, no checkpoint/ here
        json.dump(metrics, f)
    with open(run / "metrics.json", "w") as f:
        json.dump(metrics, f)
    save_state(str(run / "checkpoint" / "ckpt_1_0.ckpt"), {"agent": {"w": np.full((2,), 7.0)}, "iter_num": 1})

    out = manager.register_best_models(str(exp), {"agent"})
    assert set(out) == {"agent"}
    assert np.allclose(manager.load_model("agent")["w"], 7.0)


def test_version_config_roundtrip(manager, tmp_path):
    """Serving a registry version by name needs the run config stored next to
    the weights (sheeprl-serve model_name=...)."""
    from sheeprl_tpu.utils.utils import dotdict

    art = _artifact(tmp_path)
    v1 = manager.register_model(art, "m")
    manager.register_model(art, "m")
    cfg = dotdict({"algo": {"name": "ppo"}, "seed": 7})
    path = manager.save_version_config("m", v1.version, cfg)
    assert os.path.isfile(path)
    loaded = manager.load_version_config("m", v1.version)
    assert loaded.algo.name == "ppo"
    assert loaded.seed == 7
    # v2 was registered without a config: actionable failure, not a silent None
    with pytest.raises(FileNotFoundError, match="checkpoint_path"):
        manager.load_version_config("m", 2)
    with pytest.raises(ValueError, match="no version 99"):
        manager.save_version_config("m", 99, cfg)


def test_registration_cli_stores_version_config(standard_args, tmp_path, monkeypatch):
    """The registration flow must leave each version servable by name: weights
    AND the producing run config."""
    from sheeprl_tpu.cli import registration, run

    monkeypatch.chdir(tmp_path)
    run(
        overrides=standard_args
        + [
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "fabric.devices=1",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=2",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "buffer.memmap=False",
            "env.num_envs=1",
            "checkpoint.save_last=True",
        ]
    )
    ckpts = []
    for root, _, files in os.walk(tmp_path / "logs"):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    registry = tmp_path / "registry"
    registration(overrides=[f"checkpoint_path={ckpts[0]}", f"model_manager.registry_dir={registry}"])
    name = os.listdir(registry)[0]
    assert (registry / name / "v1" / "config.yaml").is_file()
    mgr = LocalModelManager(_FakeRuntime(), str(registry))
    cfg = mgr.load_version_config(name)
    assert cfg.algo.name == "ppo"
    assert "state" in cfg.algo.mlp_keys.encoder


def test_evaluation_prefers_certified_sibling(tmp_path, monkeypatch):
    """evaluation() must evaluate the certified sibling when the requested
    checkpoint is uncertified, and honor prefer_certified=False."""
    import warnings

    import yaml

    from sheeprl_tpu import cli
    from sheeprl_tpu.utils.checkpoint import certify, save_state

    run_dir = tmp_path / "run"
    ckpt_dir = run_dir / "checkpoint"
    os.makedirs(ckpt_dir)
    with open(run_dir / "config.yaml", "w") as f:
        yaml.safe_dump({"env": {"num_envs": 4, "capture_video": False}, "fabric": {"devices": 2}}, f)
    good = str(ckpt_dir / "ckpt_100_0.ckpt")
    info = save_state(good, {"agent": {"w": 1.0}})
    certify(good, crc32=info.get("crc32"), size=info.get("size"))
    bad = str(ckpt_dir / "ckpt_200_0.ckpt")
    save_state(bad, {"agent": {"w": 2.0}})  # newer but uncertified

    seen = {}
    monkeypatch.setattr(cli, "check_configs_evaluation", lambda cfg: None)
    monkeypatch.setattr(cli, "eval_algorithm", lambda cfg: seen.update(ckpt=cfg.checkpoint_path))
    with pytest.warns(UserWarning, match="not certified"):
        cli.evaluation(overrides=[f"checkpoint_path={bad}"])
    assert seen["ckpt"] == good
    # the literal (certified) path needs no redirect and no warning
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        cli.evaluation(overrides=[f"checkpoint_path={good}"])
    assert seen["ckpt"] == good
    # opting out pins the literal uncertified path
    cli.evaluation(overrides=[f"checkpoint_path={bad}", "prefer_certified=False"])
    assert seen["ckpt"] == bad
