"""parallel/control.py: host control plane over a real socket KV pair —
collectives, epoch fencing, fault-injected chunk transport, liveness, and the
actionable-unavailability path (satellite of the multihost rewiring)."""

import threading
import zlib

import pytest

from sheeprl_tpu.core import failpoints
from sheeprl_tpu.parallel import control
from sheeprl_tpu.parallel.control import (
    ControlPlane,
    KVServer,
    KVUnavailableError,
    SocketKV,
    StaleEpochError,
)


@pytest.fixture()
def kv_pair():
    server = KVServer()
    server.start()
    try:
        yield SocketKV(server.address), SocketKV(server.address)
    finally:
        server.stop()


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def _planes(kv_pair, scope, **kw):
    a, b = kv_pair
    return (
        ControlPlane(a, rank=0, world=2, scope=scope, timeout_ms=20_000, **kw),
        ControlPlane(b, rank=1, world=2, scope=scope, timeout_ms=20_000, **kw),
    )


def _join(*threads):
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "control-plane thread wedged"


# --------------------------------------------------------------------------- #
# collectives
# --------------------------------------------------------------------------- #


def test_broadcast_barrier_and_gather_across_two_ranks(kv_pair):
    p0, p1 = _planes(kv_pair, "collectives")
    got = {}

    def rank0():
        assert p0.broadcast_str("log_dir", "logs/run-1") == "logs/run-1"
        p0.barrier("setup")
        got[0] = p0.all_gather_meta("caps", {"rank": 0, "envs": 4})

    def rank1():
        got["bcast"] = p1.broadcast_str("log_dir")
        p1.barrier("setup")
        got[1] = p1.all_gather_meta("caps", {"rank": 1, "envs": 2})

    _join(threading.Thread(target=rank0), threading.Thread(target=rank1))
    assert got["bcast"] == "logs/run-1"
    assert got[0] == got[1] == {0: {"rank": 0, "envs": 4}, 1: {"rank": 1, "envs": 2}}


def test_broadcast_repeats_under_one_name_stay_matched(kv_pair):
    p0, p1 = _planes(kv_pair, "bcast_seq")
    seen = []

    def rank0():
        for v in ("first", "second"):
            p0.broadcast_str("v", v)

    def rank1():
        seen.extend(p1.broadcast_str("v") for _ in range(2))

    _join(threading.Thread(target=rank0), threading.Thread(target=rank1))
    assert seen == ["first", "second"]


# --------------------------------------------------------------------------- #
# chunk transport under injected faults
# --------------------------------------------------------------------------- #


@pytest.mark.faults
def test_chunk_stream_survives_drops_and_torn_payloads(kv_pair):
    writer, reader = _planes(kv_pair, "chunks")
    writer.begin_session("w")
    reader.adopt_epoch("w")
    chunks = [f"payload-{i}".encode() * 20 for i in range(6)]
    out = []

    def send():
        # every 2nd attempt silently dropped, every 3rd torn mid-payload:
        # the ack/CRC protocol must still deliver the exact stream
        with failpoints.active("control.chunk_send:drop:every=2"):
            for i in (0, 1, 2):
                writer.send_chunk("c", i, chunks[i], timeout_ms=20_000)
        with failpoints.active("control.chunk_send:corrupt:3:every=3"):
            for i in (3, 4, 5):
                writer.send_chunk("c", i, chunks[i], timeout_ms=20_000)

    def recv():
        out.extend(reader.recv_chunk("c", i, timeout_ms=30_000) for i in range(6))

    _join(threading.Thread(target=send), threading.Thread(target=recv))
    assert [zlib.crc32(d) for d in out] == [zlib.crc32(d) for d in chunks]
    assert writer.counters["Resilience/chunk_resends"] >= 2
    assert reader.chunk_cursor("c") == 5


@pytest.mark.faults
def test_zombie_writer_is_fenced_and_told_to_stop(kv_pair):
    zombie, reader = _planes(kv_pair, "fence")
    successor = ControlPlane(kv_pair[0], rank=0, world=2, scope="fence", timeout_ms=20_000)
    zombie.begin_session("w")  # epoch 1
    successor.begin_session("w")  # epoch 2 — supersedes the zombie
    reader.adopt_epoch("w")
    out, errors = [], []

    def dead_then_live():
        try:
            zombie.send_chunk("c", 0, b"from-the-dead", timeout_ms=20_000)
        except StaleEpochError as e:
            errors.append(e)
        successor.send_chunk("c", 0, b"authoritative", timeout_ms=20_000)

    def recv():
        out.append(reader.recv_chunk("c", 0, timeout_ms=30_000))

    _join(threading.Thread(target=dead_then_live), threading.Thread(target=recv))
    assert out == [b"authoritative"], "reader accepted a zombie epoch's payload"
    assert len(errors) == 1, "the zombie writer was not told to stop"
    assert reader.counters["Resilience/stale_epoch_rejects"] >= 1


def test_reader_refetches_authoritative_epoch_to_fence_racing_zombie(kv_pair):
    # A zombie whose forged envelope CLAIMS the current epoch must still be
    # rejected: the reader re-reads the epoch key before accepting anything
    # at-or-above its last seen epoch.
    zombie, reader = _planes(kv_pair, "race")
    zombie.begin_session("w")  # epoch 1
    reader.adopt_epoch("w")  # reader has only seen epoch 1
    successor = ControlPlane(kv_pair[0], rank=0, world=2, scope="race", timeout_ms=20_000)
    successor.begin_session("w")  # epoch 2, but no envelope from it yet
    out = []

    def send():
        try:
            zombie.send_chunk("c", 0, b"zombie-races-ahead", timeout_ms=5_000)
        except (StaleEpochError, control.ControlPlaneTimeoutError):
            pass
        successor.send_chunk("c", 0, b"real", timeout_ms=20_000)

    def recv():
        out.append(reader.recv_chunk("c", 0, timeout_ms=30_000))

    _join(threading.Thread(target=send), threading.Thread(target=recv))
    assert out == [b"real"]
    assert reader.counters["Resilience/stale_epoch_rejects"] >= 1


# --------------------------------------------------------------------------- #
# heartbeat / liveness
# --------------------------------------------------------------------------- #


def test_heartbeat_and_peer_liveness(kv_pair):
    p0, p1 = _planes(kv_pair, "hb")
    p0.begin_session("w")
    p0.heartbeat({"iteration": 7})
    view = p1.peer_liveness(max_age_s=30.0)
    assert view[0]["alive"] is True and view[0]["seq"] == 1 and view[0]["epoch"] == 1
    assert view[1]["alive"] is False  # rank 1 never beat
    assert p0.counters["Resilience/heartbeats_sent"] == 1
    # an old beat ages out and is counted as stale
    stale = p1.peer_liveness(max_age_s=0.0)
    assert stale[0]["alive"] is False
    assert p1.counters["Resilience/peer_stale_heartbeats"] >= 1


# --------------------------------------------------------------------------- #
# unavailability diagnosis (satellite: the old silent-None _kv_client)
# --------------------------------------------------------------------------- #


def test_require_coordinator_client_diagnoses_and_counts(monkeypatch):
    monkeypatch.setattr(control, "coordinator_client", lambda: None)
    counters = {}
    with pytest.raises(KVUnavailableError, match="jax.distributed.initialize"):
        control.require_coordinator_client("the payload-spec exchange", counters)
    assert counters[control.KV_UNAVAILABLE_COUNTER] == 1


def test_decoupled_kv_probe_is_quietly_none_outside_a_jax_world():
    from sheeprl_tpu.parallel import decoupled

    assert decoupled._kv_client() is None  # no jax.distributed.initialize() here


def test_timeout_error_names_the_key_and_scope(kv_pair):
    plane = ControlPlane(kv_pair[0], rank=1, world=2, scope="diag", timeout_ms=300, retries=0)
    with pytest.raises(control.ControlPlaneTimeoutError, match="broadcast of 'never'.*rank 1.*'diag'"):
        plane.broadcast_str("never", timeout_ms=300)
