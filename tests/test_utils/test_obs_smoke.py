"""Satellite registration of scripts/obs_smoke.py as a tier-1 test: a fresh
fused-PPO run must land every AOT compile in the trace-id-stamped programs
ledger, the diff CLI must flag a doctored copy (and pass the self-diff), and
``bench.py --check-regressions`` must gate a doctored bench ledger — the
end-to-end proof that the compiled-program observatory stays wired through the
env, compile, telemetry, and bench layers."""

import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.telemetry
@pytest.mark.timeout(600)
def test_obs_smoke(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "obs_smoke.py"),
            "--workdir",
            str(tmp_path),
            "--timeout",
            "420",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-1500:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "obs smoke OK" in out.stdout
