"""Satellite registration of scripts/population_fused_smoke.py as a tier-1
test: the fused-population chaos drill — a 4-member domain-randomized CartPole
population trained as ONE compiled vmapped program through the real controller
must finish with zero retraces, heal a member_sync-poisoned member via the
in-graph exploit (resow row with a parent + perturbed hypers in
lineage.jsonl), certify per-member checkpoint slices, and classify an
exploit-seam crash as ``failed`` at ``max_failures=0`` (full harness, fresh
interpreters all the way down)."""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.faults
@pytest.mark.timeout(600)
def test_population_fused_smoke_chaos_drill(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "population_fused_smoke.py"),
            "--workdir",
            str(tmp_path),
            "--timeout",
            "480",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-2500:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "population fused smoke OK" in out.stdout
    # the drill's own assertions already ran; independently re-check the two
    # population-level artifacts it leaves behind
    with open(tmp_path / "fused_healthy" / "lineage.jsonl") as f:
        edges = [json.loads(line) for line in f if line.strip()]
    assert sum(1 for e in edges if e["kind"] == "seed") == 4
    healed = [e for e in edges if e["kind"] == "resow" and e["trial"] == "m01" and e.get("parent")]
    assert healed, [e["kind"] for e in edges]
    with open(tmp_path / "fused_healthy" / "population" / "fitness.jsonl") as f:
        rows = [json.loads(line) for line in f if line.strip()]
    poisoned = [r for r in rows if r["kind"] == "epoch" and r.get("bad_members")]
    assert poisoned and 1 in poisoned[0]["bad_members"]
