"""Every shipped experiment recipe must compose (config-rot guard).

P2E finetuning recipes intentionally require checkpoint.exploration_ckpt_path
(mandatory ``???``), so they compose only with it supplied.
"""

import os

import pytest

from sheeprl_tpu.config import compose

_EXP_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "sheeprl_tpu",
    "configs",
    "exp",
)
ALL_EXPS = sorted(f[:-5] for f in os.listdir(_EXP_DIR) if f.endswith(".yaml") and f != "default.yaml")


@pytest.mark.parametrize("exp", ALL_EXPS)
def test_exp_recipe_composes(exp):
    overrides = [f"exp={exp}"]
    if "fntn" in exp or "finetuning" in exp:
        overrides.append("checkpoint.exploration_ckpt_path=/tmp/placeholder.ckpt")
    cfg = compose(overrides=overrides)
    assert cfg.algo.name
    assert cfg.env.wrapper.get("_target_")
    assert cfg.fabric.precision in ("32-true", "32", "bf16-mixed", "bf16-true", "16-mixed")
