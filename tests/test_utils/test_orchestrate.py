"""Unit layer of the elastic population controller (sheeprl_tpu/orchestrate/):
trial state machine, crash-safe journal, slot scheduler, exploit/explore resow
policy, lineage reconstruction, health-event tailing, and the full controller
loop driven against a stub trainee (no jax import) — including killing the
controller mid-drill and resuming from the journal."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from sheeprl_tpu.core.health import read_events
from sheeprl_tpu.orchestrate import resolve
from sheeprl_tpu.orchestrate import trial as T
from sheeprl_tpu.orchestrate.controller import ENTRY_ENV_VAR, PopulationController
from sheeprl_tpu.orchestrate.journal import Journal
from sheeprl_tpu.orchestrate.lineage import LineageLog, ancestry, read_lineage
from sheeprl_tpu.orchestrate.resow import bottom_quantile, perturb, select_parent
from sheeprl_tpu.orchestrate.scheduler import SlotScheduler
from sheeprl_tpu.orchestrate.trial import IllegalTransition, Trial, TrialSpec

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# --------------------------------------------------------------------------- #
# Trial state machine
# --------------------------------------------------------------------------- #


def _trial(key="t0", **kw):
    return Trial(TrialSpec(key=key, overrides=["exp=ppo"], **kw))


def test_trial_legal_lifecycle_and_history():
    t = _trial()
    t.to(T.RUNNING)
    t.to(T.PREEMPTED)
    t.to(T.RESUMED)
    t.to(T.RUNNING)
    t.to(T.DIVERGED)
    t.generation += 1
    t.to(T.RESOWN)
    t.to(T.RUNNING)
    t.to(T.COMPLETED)
    assert t.terminal
    assert [h["state"] for h in t.history] == [
        T.RUNNING, T.PREEMPTED, T.RESUMED, T.RUNNING,
        T.DIVERGED, T.RESOWN, T.RUNNING, T.COMPLETED,
    ]


def test_trial_illegal_transitions_raise():
    t = _trial()
    with pytest.raises(IllegalTransition, match="pending -> completed"):
        t.to(T.COMPLETED)
    t.to(T.RUNNING)
    t.to(T.COMPLETED)
    with pytest.raises(IllegalTransition):  # terminal states are sinks
        t.to(T.RUNNING)


def test_trial_serialization_roundtrip():
    t = _trial(hyperparams={"algo.optimizer.lr": 1e-3}, chaos_overrides=["env.wrapper.x=1"])
    t.to(T.RUNNING, pid=123)
    t.to(T.PREEMPTED)
    t.resume_ckpt = "/tmp/ckpt_16_0.ckpt"
    back = Trial.from_dict(json.loads(json.dumps(t.to_dict())))
    assert back.key == t.key and back.state == T.PREEMPTED
    assert back.spec.chaos_overrides == ["env.wrapper.x=1"]
    assert back.hyperparams == {"algo.optimizer.lr": 1e-3}
    assert back.resume_ckpt == t.resume_ckpt
    assert back.history == t.history


# --------------------------------------------------------------------------- #
# Journal
# --------------------------------------------------------------------------- #


def test_journal_roundtrip_and_atomic_replace(tmp_path):
    journal = Journal(str(tmp_path / "journal.json"))
    assert journal.load() is None and journal.load_trials() == []
    trials = [_trial("t0"), _trial("t1")]
    trials[0].to(T.RUNNING)
    journal.save(trials, {"spawn_seq": 2})
    loaded = journal.load_trials()
    assert [t.key for t in loaded] == ["t0", "t1"]
    assert loaded[0].state == T.RUNNING
    assert journal.load()["counters"]["spawn_seq"] == 2
    # a second save fully replaces the snapshot and leaves no temp debris
    journal.save(trials[:1], {})
    assert len(journal.load_trials()) == 1
    assert not os.path.exists(journal.path + ".tmp")


# --------------------------------------------------------------------------- #
# SlotScheduler
# --------------------------------------------------------------------------- #


def test_scheduler_respects_slots_and_eligibility():
    sched = SlotScheduler(slots=2)
    trials = [_trial(f"t{i}") for i in range(4)]
    picked = sched.next_to_run(trials, now=100.0)
    assert [t.key for t in picked] == ["t0", "t1"]  # capped at free slots
    trials[0].to(T.RUNNING)
    picked = sched.next_to_run(trials, now=100.0)
    assert [t.key for t in picked] == ["t1"]  # one slot taken
    trials[1].next_eligible = 200.0  # backing off: not eligible yet
    assert [t.key for t in sched.next_to_run(trials, now=100.0)] == ["t2"]


def test_scheduler_preemption_requeues_with_jittered_backoff():
    import random

    sched = SlotScheduler(slots=1, max_preemptions=2, rng=random.Random(0))
    t = _trial()
    t.to(T.RUNNING)
    t.to(T.PREEMPTED)
    assert sched.requeue_preempted(t, "/ck/pt.ckpt", now=50.0) == T.RESUMED
    assert t.resume_ckpt == "/ck/pt.ckpt"
    delay = t.next_eligible - 50.0
    # jittered envelope of attempt 1: uniform(0.5, 1.0) * base(0.5)
    assert 0.25 <= delay <= 0.5
    # attempt 2 doubles the nominal backoff: uniform(0.5, 1.0) * 1.0
    t.to(T.RUNNING)
    t.to(T.PREEMPTED)
    assert sched.requeue_preempted(t, "/ck/pt.ckpt", now=60.0) == T.RESUMED
    assert 0.5 <= t.next_eligible - 60.0 <= 1.0
    # past the budget the trial is terminal
    t.to(T.RUNNING)
    t.to(T.PREEMPTED)
    assert t.preemptions == 2
    assert sched.requeue_preempted(t, None, now=70.0) == T.FAILED


def test_scheduler_failure_budget():
    sched = SlotScheduler(slots=1, max_failures=1, backoff_base_s=0.0)
    t = _trial()
    t.to(T.RUNNING)
    assert sched.requeue_failed(t, "rc=1", now=10.0) == T.RESUMED
    t.to(T.RUNNING)
    assert sched.requeue_failed(t, "rc=1", now=11.0) == T.FAILED


# --------------------------------------------------------------------------- #
# Resow policy
# --------------------------------------------------------------------------- #


def _fake_certified(dirpath, name, step):
    os.makedirs(dirpath, exist_ok=True)
    ckpt = os.path.join(dirpath, name)
    with open(ckpt, "wb") as f:
        f.write(b"weights")
    with open(ckpt + ".certified.json", "w") as f:
        json.dump({"certified": True, "ckpt": name, "crc32": None, "size": 7, "policy_step": step}, f)
    return ckpt


def test_select_parent_prefers_highest_certified_step(tmp_path):
    dirs = {k: str(tmp_path / k) for k in ("a", "b", "c")}
    _fake_certified(dirs["a"], "ckpt_16_0.ckpt", 16)
    _fake_certified(dirs["b"], "ckpt_48_0.ckpt", 48)
    os.makedirs(dirs["c"], exist_ok=True)  # never certified anything
    key, ckpt, step = select_parent(dirs)
    assert key == "b" and step == 48 and ckpt.endswith("ckpt_48_0.ckpt")
    # excluding the leader falls through to the runner-up; excluding all -> None
    assert select_parent(dirs, exclude=["b"])[0] == "a"
    assert select_parent(dirs, exclude=["a", "b"]) is None


def test_select_parent_ignores_uncertified_checkpoints(tmp_path):
    dirs = {"a": str(tmp_path / "a"), "b": str(tmp_path / "b")}
    os.makedirs(dirs["a"], exist_ok=True)
    with open(os.path.join(dirs["a"], "ckpt_99_0.ckpt"), "wb") as f:
        f.write(b"poisoned")  # newest but uncertified: never a parent
    _fake_certified(dirs["b"], "ckpt_8_0.ckpt", 8)
    assert select_parent(dirs)[0] == "b"


def test_perturb_only_touches_declared_numeric_keys():
    import random

    out = perturb(
        {"algo.optimizer.lr": 1e-3, "algo.ent_coef": "auto", "algo.clip": True},
        keys=["algo.optimizer.lr", "algo.ent_coef", "algo.clip", "algo.missing"],
        factors=[2.0],
        rng=random.Random(1),
    )
    assert out["algo.optimizer.lr"] == pytest.approx(2e-3)
    assert out["algo.ent_coef"] == "auto"  # non-numeric untouched
    assert out["algo.clip"] is True  # bools are not numbers here
    assert "algo.missing" not in out  # never invents a hyperparameter


def test_bottom_quantile_returns_at_least_one():
    fits = {"a": 10, "b": 2, "c": 5, "d": 7}
    assert bottom_quantile(fits, 0.25) == ["b"]
    assert bottom_quantile(fits, 0.5) == ["b", "c"]
    assert bottom_quantile({}, 0.5) == []
    assert bottom_quantile(fits, 0.0) == []


# --------------------------------------------------------------------------- #
# Lineage
# --------------------------------------------------------------------------- #


def test_lineage_ancestry_walks_resow_edges(tmp_path):
    log = LineageLog(str(tmp_path / "lineage.jsonl"))
    log.record("seed", "a", 0)
    log.record("seed", "b", 0)
    log.record("resume", "a", 0)
    log.record("resow", "b", 1, parent="a", ckpt="/x/ckpt_32_0.ckpt", hyperparams={"lr": 2e-3})
    log.record("resume", "a", 0)  # after the resow: not part of b's ancestry
    chain = ancestry(str(tmp_path / "lineage.jsonl"), "b")
    kinds = [(e["kind"], e["trial"]) for e in chain]
    assert kinds == [("seed", "a"), ("resume", "a"), ("seed", "b"), ("resow", "b")]
    assert read_lineage(str(tmp_path / "missing.jsonl")) == []


# --------------------------------------------------------------------------- #
# Health event tailing (core/health.read_events)
# --------------------------------------------------------------------------- #


def test_read_events_incremental_offsets_and_torn_tail(tmp_path):
    path = tmp_path / "events.jsonl"
    path.write_text(json.dumps({"event": "warn"}) + "\n")
    events, off = read_events(str(path), 0)
    assert [e["event"] for e in events] == ["warn"]
    # nothing new: same offset, no re-parse
    events, off2 = read_events(str(path), off)
    assert events == [] and off2 == off
    # a torn final line (writer mid-append) is left for the next call
    with open(path, "a") as f:
        f.write(json.dumps({"event": "backoff"}) + "\n")
        f.write('{"event": "roll')
    events, off3 = read_events(str(path), off)
    assert [e["event"] for e in events] == ["backoff"]
    with open(path, "a") as f:
        f.write('back"}\n')
    events, _ = read_events(str(path), off3)
    assert [e["event"] for e in events] == ["rollback"]


def test_read_events_accepts_directory_and_missing_file(tmp_path):
    (tmp_path / "events.jsonl").write_text('{"event": "warn"}\n')
    events, _ = read_events(str(tmp_path), 0)  # health/ dir, not the file
    assert len(events) == 1
    assert read_events(str(tmp_path / "nope" / "events.jsonl"), 0) == ([], 0)


# --------------------------------------------------------------------------- #
# Controller end-to-end against a stub trainee (no jax)
# --------------------------------------------------------------------------- #

# Emulates exactly the contract the controller relies on: touches the guard
# ready file, writes (and certifies) checkpoints, appends health events, turns
# SIGTERM into flag-file + final checkpoint + exit 0, resumes from
# checkpoint.resume_from, and diverges on demand via a stub.diverge_at override.
_STUB_TRAINEE = textwrap.dedent(
    """
    import json, os, signal, sys, time

    cfg = {}
    for arg in sys.argv[1:]:
        k, _, v = arg.partition("=")
        cfg[k] = v

    run_name = cfg["run_name"]
    run_dir = os.path.join(os.getcwd(), "logs", run_name)
    ckpt_dir = os.path.join(run_dir, "checkpoints")
    health_dir = os.path.join(run_dir, "health")
    os.makedirs(ckpt_dir, exist_ok=True)
    os.makedirs(health_dir, exist_ok=True)

    start = 0
    resume = cfg.get("checkpoint.resume_from")
    if resume:
        with open(resume) as f:
            start = json.load(f)["iter"]

    stopping = {"flag": False}

    def _on_term(signum, frame):
        stopping["flag"] = True
        flag = os.environ.get("SHEEPRL_PREEMPTION_FLAG_FILE")
        if flag:
            with open(flag, "w") as f:
                f.write(str(signum))

    signal.signal(signal.SIGTERM, _on_term)

    ready = os.environ.get("SHEEPRL_PREEMPTION_READY_FILE")
    if ready:
        with open(ready, "w") as f:
            f.write(str(os.getpid()))

    total = int(cfg.get("stub.total_iters", "40"))
    diverge_at = int(cfg.get("stub.diverge_at", "-1"))
    tick = float(cfg.get("stub.tick_s", "0.05"))

    def save(i, certified):
        path = os.path.join(ckpt_dir, "ckpt_%d_0.ckpt" % i)
        with open(path, "w") as f:
            json.dump({"iter": i, "lr": cfg.get("algo.optimizer.lr")}, f)
        if certified:
            with open(path + ".certified.json", "w") as f:
                json.dump({"certified": True, "ckpt": os.path.basename(path),
                           "crc32": None, "size": os.path.getsize(path),
                           "policy_step": i}, f)

    for i in range(start, total):
        time.sleep(tick)
        if stopping["flag"]:
            save(i, certified=False)  # emergency checkpoint: never certified
            sys.exit(0)
        if i and i % 5 == 0:
            save(i, certified=True)
        if diverge_at >= 0 and i >= diverge_at and not resume:
            with open(os.path.join(health_dir, "events.jsonl"), "a") as f:
                f.write(json.dumps({"event": "warn", "reason": "divergence: Loss/value_loss", "step": i}) + "\\n")
                f.flush()
            # a diverged run would thrash on forever; the controller must kill us
            # (PEP 475: one long sleep would NOT be interrupted by the handled
            # signal, so poll the stop flag instead)
            for _ in range(1200):
                if stopping["flag"]:
                    save(i, certified=False)
                    sys.exit(0)
                time.sleep(0.05)
            sys.exit(7)
    save(total, certified=True)
    sys.exit(0)
    """
)


@pytest.fixture()
def stub_entry(tmp_path, monkeypatch):
    entry = tmp_path / "stub_trainee.py"
    entry.write_text(_STUB_TRAINEE)
    monkeypatch.setenv(ENTRY_ENV_VAR, str(entry))
    return entry


def _specs(n_clean=2, chaos=True, total=20, tick=0.02):
    specs = [
        TrialSpec(
            key=f"t{i}",
            overrides=[f"stub.total_iters={total}", f"stub.tick_s={tick}"],
            hyperparams={"algo.optimizer.lr": 1e-3},
        )
        for i in range(n_clean)
    ]
    if chaos:
        specs.append(
            TrialSpec(
                key="t_chaos",
                overrides=[f"stub.total_iters={total}", f"stub.tick_s={tick}"],
                hyperparams={"algo.optimizer.lr": 1e-3},
                chaos_overrides=["stub.diverge_at=8"],
            )
        )
    return specs


_POLICY = {
    "orchestrate": {
        "slots": 2,
        "poll_interval_s": 0.05,
        "trial": {"requeue_backoff_base_s": 0.05, "requeue_backoff_max_s": 0.2},
        "resow": {"parent_wait_s": 20.0, "perturb": {"keys": ["algo.optimizer.lr"], "factors": [0.8, 1.25]}},
        "shutdown": {"drain_timeout_s": 20.0},
    }
}


@pytest.mark.timeout(120)
def test_controller_completes_clean_population(stub_entry, tmp_path):
    ctrl = PopulationController(_specs(n_clean=2, chaos=False), str(tmp_path / "state"), cfg=_POLICY)
    assert ctrl.run(max_runtime_s=60.0) == "done"
    assert all(t.state == T.COMPLETED for t in ctrl.trials)
    edges = read_lineage(str(tmp_path / "state" / "lineage.jsonl"))
    assert [e["kind"] for e in edges] == ["seed", "seed"]


@pytest.mark.timeout(120)
def test_controller_resows_diverged_trial_from_certified_peer(stub_entry, tmp_path):
    ctrl = PopulationController(_specs(n_clean=1, chaos=True), str(tmp_path / "state"), cfg=_POLICY)
    assert ctrl.run(max_runtime_s=90.0) == "done"
    chaos = next(t for t in ctrl.trials if t.key == "t_chaos")
    assert chaos.state == T.COMPLETED
    assert chaos.generation >= 1 and chaos.parent == "t0"
    edges = read_lineage(str(tmp_path / "state" / "lineage.jsonl"))
    resows = [e for e in edges if e["kind"] == "resow"]
    assert len(resows) >= 1
    # resown from the PEER's certified checkpoint, not from scratch
    assert resows[0]["parent"] == "t0" and "/t0/" in resows[0]["ckpt"]
    assert os.path.exists(resows[0]["ckpt"] + ".certified.json")
    # the explore step actually perturbed the declared hyperparameter
    lr = resows[0]["hyperparams"]["algo.optimizer.lr"]
    assert lr in (pytest.approx(0.8e-3), pytest.approx(1.25e-3))
    # ancestry of the resown trial reaches back through the parent's seed edge
    kinds = [(e["kind"], e["trial"]) for e in ancestry(str(tmp_path / "state" / "lineage.jsonl"), "t_chaos")]
    assert ("seed", "t0") in kinds and ("resow", "t_chaos") in kinds


@pytest.mark.timeout(120)
def test_controller_injected_preemptions_resume_from_own_checkpoint(stub_entry, tmp_path):
    ctrl = PopulationController(
        _specs(n_clean=2, chaos=False, total=60, tick=0.05),
        str(tmp_path / "state"),
        cfg=_POLICY,
        inject_preempt=2,
        inject_spacing_s=0.3,
    )
    assert ctrl.run(max_runtime_s=90.0) == "done"
    assert ctrl.counters["injections"] == 2
    assert all(t.state == T.COMPLETED for t in ctrl.trials)
    preempted = [t for t in ctrl.trials if t.preemptions]
    assert sum(t.preemptions for t in ctrl.trials) == 2
    # every preempted trial resumed from a checkpoint (resume lineage edge with ckpt)
    edges = read_lineage(str(tmp_path / "state" / "lineage.jsonl"))
    resumes = [e for e in edges if e["kind"] == "resume"]
    assert len(resumes) == 2
    assert all(e["ckpt"] and e["ckpt"].endswith(".ckpt") for e in resumes)
    assert ctrl.counters["preempt_recoveries"], "recovery latency not recorded"


def _run_controller_subprocess(spec_path, state_dir, entry, extra=()):
    env = dict(os.environ, **{ENTRY_ENV_VAR: str(entry)})
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "sheeprl_tpu.orchestrate.controller",
            "--spec",
            str(spec_path),
            "--state-dir",
            str(state_dir),
            *extra,
        ],
        cwd=REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


@pytest.mark.timeout(180)
def test_controller_killed_mid_drill_resumes_from_journal(stub_entry, tmp_path):
    """Acceptance criterion: SIGTERM the controller mid-drill, restart it with
    the same --state-dir, and the fleet resumes with no duplicated or lost
    trials (journal reconciliation + preemption-guard fan-out)."""
    spec_path = tmp_path / "population.json"
    spec_path.write_text(
        json.dumps(
            {
                **_POLICY,
                "trials": [s.to_dict() for s in _specs(n_clean=2, chaos=False, total=80, tick=0.05)],
            }
        )
    )
    state_dir = tmp_path / "state"
    proc = _run_controller_subprocess(spec_path, state_dir, stub_entry)
    journal = state_dir / "journal.json"
    deadline = time.time() + 60.0
    running = []
    while time.time() < deadline:
        if journal.exists():
            snap = json.loads(journal.read_text())
            running = [t for t in snap.get("trials", []) if t["state"] == "running"]
            if len(running) == 2:
                break
        time.sleep(0.1)
    assert len(running) == 2, "fleet never reached 2 running trials"
    time.sleep(1.0)  # let the stubs write their first certified checkpoints
    proc.send_signal(signal.SIGTERM)
    assert proc.wait(timeout=60) == 0  # "preempted" is a clean controller exit
    out1 = proc.stdout.read()
    assert '"status": "preempted"' in out1

    # journal after the kill: both trials requeued, neither lost nor duplicated
    snap = json.loads(journal.read_text())
    assert sorted(t["spec"]["key"] for t in snap["trials"]) == ["t0", "t1"]
    assert all(t["state"] in ("resumed", "preempted") for t in snap["trials"])

    proc = _run_controller_subprocess(spec_path, state_dir, stub_entry)
    rc = proc.wait(timeout=120)
    out2 = proc.stdout.read()
    assert rc == 0, out2[-2000:]
    summary = json.loads(out2.splitlines()[-1].split("ORCHESTRATE_RESULT ", 1)[1])
    assert summary["status"] == "done"
    assert sorted(summary["trials"]) == ["t0", "t1"]
    assert all(v["state"] == "completed" for v in summary["trials"].values())
    assert summary["counters"]["controller_incarnations"] == 2
    # exactly one seed edge per trial across BOTH controller incarnations: the
    # restart resumed the journaled trials instead of re-seeding them
    edges = read_lineage(str(state_dir / "lineage.jsonl"))
    assert sum(1 for e in edges if e["kind"] == "seed") == 2
    resumed = [e for e in edges if e["kind"] == "resume"]
    assert len(resumed) >= 2  # both trials came back after the controller kill
    # the resumed incarnations picked up each trial's own newest checkpoint
    assert all(e["ckpt"] for e in resumed)
    # no orphaned trial subprocesses: every journaled pid is dead
    snap = json.loads(journal.read_text())
    for t in snap["trials"]:
        if t.get("pid"):
            with pytest.raises(OSError):
                os.kill(int(t["pid"]), 0)


@pytest.mark.timeout(120)
def test_controller_reconciles_orphans_after_hard_kill(stub_entry, tmp_path):
    """SIGKILL (no drain, no journal update) leaves RUNNING entries whose
    processes may still be alive: the restarted controller must terminate the
    orphans and requeue their trials rather than double-spawning them."""
    spec_path = tmp_path / "population.json"
    spec_path.write_text(
        json.dumps(
            {
                **_POLICY,
                "trials": [s.to_dict() for s in _specs(n_clean=1, chaos=False, total=300, tick=0.05)],
            }
        )
    )
    state_dir = tmp_path / "state"
    proc = _run_controller_subprocess(spec_path, state_dir, stub_entry)
    journal = state_dir / "journal.json"
    orphan_pid = None
    deadline = time.time() + 60.0
    while time.time() < deadline:
        if journal.exists():
            snap = json.loads(journal.read_text())
            pids = [t.get("pid") for t in snap.get("trials", []) if t["state"] == "running"]
            if pids and pids[0]:
                orphan_pid = pids[0]
                break
        time.sleep(0.1)
    assert orphan_pid, "trial never started"
    proc.kill()  # controller dies WITHOUT forwarding anything
    proc.wait(timeout=30)
    os.kill(orphan_pid, 0)  # trainee survived its controller: it is an orphan

    proc = _run_controller_subprocess(spec_path, state_dir, stub_entry)
    rc = proc.wait(timeout=90)
    out = proc.stdout.read()
    assert rc == 0, out[-2000:]
    assert '"status": "done"' in out
    assert "reconcile: orphan pid" in out
    with pytest.raises(OSError):  # orphan was terminated, not leaked
        os.kill(orphan_pid, 0)
    edges = read_lineage(str(state_dir / "lineage.jsonl"))
    assert sum(1 for e in edges if e["kind"] == "seed") == 1  # not re-seeded


# --------------------------------------------------------------------------- #
# resolve()
# --------------------------------------------------------------------------- #


def test_resolve_fills_defaults_and_accepts_bare_group():
    cfg = resolve(None)
    assert cfg.slots == 2 and cfg.resow.enabled is True
    cfg = resolve({"orchestrate": {"slots": 5, "resow": {"max_per_trial": 1}}})
    assert cfg.slots == 5
    assert cfg.resow.max_per_trial == 1
    assert cfg.resow.enabled is True  # untouched keys keep defaults
    cfg = resolve({"slots": 3})  # bare group dict (population spec style)
    assert cfg.slots == 3
