"""Unit layer of the fault-tolerant runtime (core/resilience.py): config
resolution, the preemption guard, crash/hang env supervision, the in-graph
non-finite guard, and the CrossHostTransport deadline/retry policy."""

import os
import signal
import time

import gymnasium as gym
import numpy as np
import pytest

from sheeprl_tpu.core import resilience
from sheeprl_tpu.core.resilience import (
    PreemptionGuard,
    SupervisedVectorEnv,
    WorkerSupervisionError,
    WorkerSupervisor,
)

# --------------------------------------------------------------------------- #
# Fixture envs (module-level so AsyncVectorEnv workers can rebuild them)
# --------------------------------------------------------------------------- #


class _FlakyEnv(gym.Env):
    """Raises on the next `fail_box[0]` step calls; state lives OUTSIDE the
    instance so a supervisor rebuild (fresh instance, same box) sees it."""

    observation_space = gym.spaces.Box(-10, 10, (2,), np.float32)
    action_space = gym.spaces.Discrete(2)

    def __init__(self, fail_box):
        self._fail_box = fail_box

    def reset(self, *, seed=None, options=None):
        return np.zeros(2, np.float32), {}

    def step(self, action):
        if self._fail_box[0] > 0:
            self._fail_box[0] -= 1
            raise RuntimeError("injected worker crash")
        return np.ones(2, np.float32), 1.0, False, False, {}


def _hanging_env_fn():
    from sheeprl_tpu.envs.chaos import ChaosEnv

    return ChaosEnv(_FlakyEnv([0]), hang_at=[2], hang_seconds=30.0)


def _healthy_env_fn():
    return _FlakyEnv([0])


# --------------------------------------------------------------------------- #
# resolve()
# --------------------------------------------------------------------------- #


def test_resolve_fills_defaults_when_group_missing():
    """Sidecar configs written before the subsystem existed lack the group."""
    ft = resilience.resolve({})
    assert ft.preemption.enabled is True
    assert ft.preemption.stop_after_iters is None
    assert ft.nonfinite.policy == "skip_update"
    assert ft.env_supervision.enabled is True
    assert ft.env_supervision.max_restarts == 3
    assert ft.transport.retries == 2


def test_resolve_partial_override_keeps_other_defaults():
    ft = resilience.resolve({"fault_tolerance": {"nonfinite": {"policy": "halt"}}})
    assert ft.nonfinite.policy == "halt"
    assert ft.env_supervision.enabled is True  # untouched section keeps defaults
    ft = resilience.resolve(
        {"fault_tolerance": {"env_supervision": {"max_restarts": 7}}}
    )
    assert ft.env_supervision.max_restarts == 7
    assert ft.env_supervision.backoff_base_s == 0.5


# --------------------------------------------------------------------------- #
# PreemptionGuard
# --------------------------------------------------------------------------- #


def test_preemption_guard_stop_after_iters():
    with PreemptionGuard(enabled=True, stop_after_iters=2) as guard:
        assert not guard.should_stop
        guard.completed_iteration()
        assert not guard.should_stop
        # mid-iteration 2: the in-band broadcast decision must already be True
        assert guard.stop_at_iteration_end()
        guard.completed_iteration()
        assert guard.should_stop
        assert "stop_after_iters=2" in guard.describe()


def test_preemption_guard_real_sigterm_and_handler_restore():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard(enabled=True) as guard:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5.0
        while not guard.should_stop and time.time() < deadline:
            time.sleep(0.01)
        assert guard.should_stop
        assert guard.signum == signal.SIGTERM
        assert "SIGTERM" in guard.describe()
        assert guard.stop_at_iteration_end()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_preemption_guard_disabled_installs_no_handlers():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard(enabled=False) as guard:
        assert signal.getsignal(signal.SIGTERM) is prev
        assert not guard.should_stop


def test_preemption_guard_touches_ready_file(tmp_path, monkeypatch):
    """The chaos harness polls this file so its SIGTERM lands mid-iteration."""
    ready = tmp_path / "guard_ready"
    monkeypatch.setenv(resilience.READY_FILE_ENV_VAR, str(ready))
    assert not ready.exists()
    with PreemptionGuard(enabled=True):
        assert ready.exists()
        assert ready.read_text() == str(os.getpid())


def test_preemption_guard_forwards_signal_to_registered_children(tmp_path):
    """A preempted CONTROLLER must SIGTERM its trial subprocesses (each runs its
    own guard and writes its own emergency checkpoint) instead of orphaning
    them — opt-in via forward_to_children (population controller satellite)."""
    import subprocess
    import sys
    import textwrap

    marker = tmp_path / "child_got_sigterm"
    child_src = textwrap.dedent(
        f"""
        import signal, sys, time
        def handler(signum, frame):
            open({str(marker)!r}, "w").write(str(signum))
            sys.exit(0)
        signal.signal(signal.SIGTERM, handler)
        print("armed", flush=True)
        for _ in range(600):
            time.sleep(0.05)
        sys.exit(1)
        """
    )
    child = subprocess.Popen([sys.executable, "-c", child_src], stdout=subprocess.PIPE, text=True)
    try:
        assert child.stdout.readline().strip() == "armed"
        with PreemptionGuard(enabled=True, forward_to_children=True) as guard:
            guard.register_child(child.pid)
            guard.register_child(child.pid)  # idempotent
            guard.register_child(99999999)  # dead/unknown pid must be skipped quietly
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.time() + 5.0
            while not guard.should_stop and time.time() < deadline:
                time.sleep(0.01)
            assert guard.should_stop
        assert child.wait(timeout=10) == 0
        assert marker.read_text() == str(int(signal.SIGTERM))
    finally:
        if child.poll() is None:
            child.kill()


def test_preemption_guard_without_forwarding_leaves_children_alone():
    import subprocess
    import sys

    child = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(30)"])
    try:
        with PreemptionGuard(enabled=True) as guard:  # forward_to_children defaults off
            guard.register_child(child.pid)  # safe no-op registration
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.time() + 5.0
            while not guard.should_stop and time.time() < deadline:
                time.sleep(0.01)
        assert child.poll() is None  # untouched
    finally:
        child.kill()
        child.wait(timeout=10)


def test_preemption_guard_touches_flag_file_on_real_signal(tmp_path, monkeypatch):
    """The flag file tells a supervising controller 'exited 0 because preempted'
    apart from 'exited 0 because finished' (byte-identical returncodes)."""
    flag = tmp_path / "preempt_flag"
    monkeypatch.setenv(resilience.FLAG_FILE_ENV_VAR, str(flag))
    with PreemptionGuard(enabled=True, stop_after_iters=1) as guard:
        guard.completed_iteration()  # the TEST knob trips the guard...
        assert guard.should_stop
    assert not flag.exists()  # ...but only a REAL signal touches the flag
    with PreemptionGuard(enabled=True) as guard:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5.0
        while not guard.should_stop and time.time() < deadline:
            time.sleep(0.01)
    assert flag.read_text() == str(int(signal.SIGTERM))


# --------------------------------------------------------------------------- #
# jittered_backoff
# --------------------------------------------------------------------------- #


def test_jittered_backoff_envelope_and_cap():
    import random

    rng = random.Random(0)
    for attempt, nominal in [(1, 0.5), (2, 1.0), (3, 2.0), (10, 30.0)]:
        for _ in range(50):
            d = resilience.jittered_backoff(0.5, attempt, 30.0, rng)
            assert 0.5 * nominal <= d <= nominal, (attempt, d)


def test_jittered_backoff_breaks_lockstep():
    """Simultaneously-killed workers must NOT all sleep the same delay — the
    whole point of the jitter is to spread the thundering herd."""
    import random

    delays = {round(resilience.jittered_backoff(1.0, 3, 60.0, random.Random(i)), 6) for i in range(20)}
    assert len(delays) > 15  # near-unique draws, never one lockstep value
    # zero-base configs (tests, hot restarts) must stay zero-delay
    assert resilience.jittered_backoff(0.0, 5, 30.0) == 0.0


# --------------------------------------------------------------------------- #
# WorkerSupervisor / SupervisedVectorEnv
# --------------------------------------------------------------------------- #


def test_worker_supervisor_restarts_crashed_env():
    fails = [1]
    sup = WorkerSupervisor(lambda: _FlakyEnv(fails), max_restarts=3, backoff_base_s=0.0)
    sup.reset()
    obs, reward, terminated, truncated, info = sup.step(0)
    # the interrupted episode is TRUNCATED (bootstrap stays legal), zero reward
    assert truncated and not terminated
    assert reward == 0.0
    assert info["worker_restarted"] is True
    assert info["restart_on_exception"] is True  # dreamer_v3's buffer-patch key
    obs, reward, terminated, truncated, info = sup.step(0)
    assert not truncated and "worker_restarted" not in info


def test_worker_supervisor_gives_up_past_max_restarts():
    fails = [100]  # persistent fault, not weather
    sup = WorkerSupervisor(lambda: _FlakyEnv(fails), max_restarts=2, backoff_base_s=0.0)
    sup.reset()
    with pytest.raises(WorkerSupervisionError, match="max_restarts=2"):
        for _ in range(10):
            sup.step(0)


def test_supervised_vector_env_counts_restarts_and_drains_deltas():
    fails = [1]
    venv = SupervisedVectorEnv(
        [lambda: _FlakyEnv(fails), lambda: _FlakyEnv([0])],
        sync=True,
        max_restarts=3,
        backoff_base_s=0.0,
    )
    try:
        venv.reset(seed=1)
        obs, rewards, terminated, truncated, info = venv.step(np.zeros(2, np.int64))
        # env 0 crashed: its episode is truncated, env 1 is untouched
        assert truncated[0] and not truncated[1]
        assert not terminated[0]
        assert venv.counters["Resilience/env_restarts"] == 1
        assert venv.counters["Resilience/env_timeouts"] == 0
        # drain returns DELTAS: first call 1, second call 0
        assert venv.drain_counters()["Resilience/env_restarts"] == 1
        assert venv.drain_counters()["Resilience/env_restarts"] == 0
        # healthy steps after the restart don't count anything
        venv.step(np.zeros(2, np.int64))
        assert venv.counters["Resilience/env_restarts"] == 1
    finally:
        venv.close()


def test_supervised_vector_env_recovers_from_hang():
    """A wedged async worker trips the per-step deadline; the parent terminates
    and rebuilds the whole vector env, truncating every in-flight episode."""
    venv = SupervisedVectorEnv(
        [_hanging_env_fn, _healthy_env_fn],
        sync=False,
        step_timeout_s=1.0,
        max_restarts=1,
        backoff_base_s=0.0,
    )
    try:
        venv.reset(seed=3)
        venv.step(np.zeros(2, np.int64))  # step 1: fine
        obs, rewards, terminated, truncated, info = venv.step(np.zeros(2, np.int64))
        assert info.get("vector_env_restarted") is True
        assert truncated.all() and not terminated.any()
        assert rewards.sum() == 0.0
        assert venv.counters["Resilience/env_timeouts"] == 1
        venv.step(np.zeros(2, np.int64))  # rebuilt group steps normally
        # the rebuilt incarnation hangs again at ITS step 2 -> budget exhausted
        with pytest.raises(WorkerSupervisionError, match="wedged"):
            venv.step(np.zeros(2, np.int64))
    finally:
        try:
            venv.close(terminate=True)
        except Exception:
            pass


def test_make_supervised_env_dispatch():
    ft_on = resilience.resolve({})
    ft_off = resilience.resolve({"fault_tolerance": {"env_supervision": {"enabled": False}}})
    venv = resilience.make_supervised_env([_healthy_env_fn], sync=True, ft=ft_on)
    assert isinstance(venv, SupervisedVectorEnv)
    venv.close()
    venv = resilience.make_supervised_env([_healthy_env_fn], sync=True, ft=ft_off)
    assert not isinstance(venv, SupervisedVectorEnv)
    venv.close()


def test_drain_env_counters_feeds_aggregator():
    from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric

    class _Fake:
        def drain_counters(self):
            return {"Resilience/env_restarts": 2, "Resilience/env_timeouts": 0}

    agg = MetricAggregator({"Resilience/env_restarts": SumMetric()})
    resilience.drain_env_counters(_Fake(), agg)
    resilience.drain_env_counters(_Fake(), agg)
    assert agg.compute()["Resilience/env_restarts"] == 4.0
    # no-ops: plain vector env (no drain_counters), disabled aggregator
    resilience.drain_env_counters(object(), agg)
    resilience.drain_env_counters(_Fake(), None)


# --------------------------------------------------------------------------- #
# In-graph non-finite guard
# --------------------------------------------------------------------------- #


def test_finite_or_skip_selects_old_state_on_nonfinite():
    import jax.numpy as jnp

    new = {"w": jnp.ones(3), "count": jnp.int32(5)}
    old = {"w": jnp.zeros(3), "count": jnp.int32(4)}

    guarded, skipped = resilience.finite_or_skip((jnp.float32(1.0),), new, old)
    assert float(skipped) == 0.0
    np.testing.assert_array_equal(np.asarray(guarded["w"]), np.ones(3))
    assert int(guarded["count"]) == 5

    for bad in (jnp.float32(np.nan), jnp.float32(np.inf), jnp.array([1.0, -np.inf])):
        guarded, skipped = resilience.finite_or_skip((jnp.float32(0.5), bad), new, old)
        assert float(skipped) == 1.0
        np.testing.assert_array_equal(np.asarray(guarded["w"]), np.zeros(3))
        assert int(guarded["count"]) == 4


def test_guard_enabled_per_policy():
    for policy, enabled in [("skip_update", True), ("halt", True), ("off", False)]:
        ft = resilience.resolve({"fault_tolerance": {"nonfinite": {"policy": policy}}})
        assert resilience.guard_enabled(ft) is enabled


def test_enforce_nonfinite_policy_halts_only_on_skips():
    ft_halt = resilience.resolve({"fault_tolerance": {"nonfinite": {"policy": "halt"}}})
    ft_skip = resilience.resolve({})
    # skip_update rides through any count
    resilience.enforce_nonfinite_policy(ft_skip, {"Resilience/nonfinite_skips": 3.0})
    # halt with zero skips (or no counter at all) is quiet
    resilience.enforce_nonfinite_policy(ft_halt, {"Resilience/nonfinite_skips": 0.0})
    resilience.enforce_nonfinite_policy(ft_halt, {})
    with pytest.raises(resilience.NonFiniteUpdateError, match="non-finite"):
        resilience.enforce_nonfinite_policy(
            ft_halt, {"Resilience/nonfinite_skips": np.float32(2.0)}
        )


# --------------------------------------------------------------------------- #
# CrossHostTransport deadline/retry policy
# --------------------------------------------------------------------------- #


def _bare_transport():
    from sheeprl_tpu.parallel.decoupled import CrossHostTransport

    # __init__ needs a trainer mesh; the fault policy is independent of it
    t = CrossHostTransport.__new__(CrossHostTransport)
    t.op_timeout_ms = None
    t.op_retries = 0
    t.op_backoff_base_s = 1.0
    t.op_backoff_max_s = 30.0
    t._scope = "unit-test-scope"
    return t


def test_transport_op_timeout_precedence():
    t = _bare_transport()
    assert t._op_timeout(5000, None) == 5000  # per-op default
    t.configure_faults(op_timeout_ms=250, retries=1, backoff_base_s=0.0)
    assert t._op_timeout(5000, None) == 250  # configured policy wins over default
    assert t._op_timeout(5000, 99) == 99  # explicit per-call override wins over all


def test_kv_retry_recovers_then_exhausts():
    from sheeprl_tpu.parallel.decoupled import TransportTimeoutError

    t = _bare_transport()
    t.configure_faults(retries=2, backoff_base_s=0.0, backoff_max_s=0.0)

    attempts = {"n": 0}

    def flaky():
        attempts["n"] += 1
        if attempts["n"] < 2:
            raise RuntimeError("transient coordinator hiccup")
        return "value"

    assert t._kv_retry(flaky, describe="KV get of 'k'") == "value"
    assert attempts["n"] == 2

    calls = []

    def dead_peer():
        calls.append(1)
        raise RuntimeError("DEADLINE_EXCEEDED: key never published")

    with pytest.raises(TransportTimeoutError) as exc:
        t._kv_retry(dead_peer, describe="KV get of 'spec'")
    assert len(calls) == 3  # 1 + retries
    msg = str(exc.value)
    # diagnosable from one log line: op, attempts, scope, underlying error
    assert "KV get of 'spec'" in msg
    assert "3 attempt(s)" in msg
    assert "DEADLINE_EXCEEDED" in msg


def test_stale_side_attribution():
    from sheeprl_tpu.parallel.decoupled import CrossHostTransport

    stale = CrossHostTransport._stale_side
    assert "TRAINER" in stale(100.0, 200.0)
    assert "PLAYER" in stale(200.0, 100.0)
    assert "unknown" in stale(None, 200.0)
    assert "unknown" in stale(100.0, None)
    assert "same mtime" in stale(100.0, 100.0)
