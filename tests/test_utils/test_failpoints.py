"""core/failpoints.py: spec grammar, deterministic triggers, actions, and the
zero-cost-when-disabled guarantee the production hot paths rely on."""

import os

import pytest

from sheeprl_tpu.core import failpoints


@pytest.fixture(autouse=True)
def _clean_registry():
    failpoints.reset()
    yield
    failpoints.reset()


# --------------------------------------------------------------------------- #
# the production guarantee: disabled means ONE None-check, nothing else
# --------------------------------------------------------------------------- #


@pytest.mark.faults
def test_disabled_failpoint_never_touches_the_registry(monkeypatch):
    def boom(*a, **k):  # any registry work while disabled is a perf regression
        raise AssertionError("failpoint() reached _fire() while disabled")

    monkeypatch.setattr(failpoints, "_fire", boom)
    assert failpoints.failpoint("ckpt.finalize", path="/nowhere") is None
    assert not failpoints.enabled()


@pytest.mark.faults
def test_unmatched_name_is_a_noop_even_when_enabled():
    failpoints.configure("other.name:raise")
    assert failpoints.failpoint("ckpt.finalize") is None
    assert failpoints.counts()["other.name"] == {"hits": 0, "fires": 0, "last_trace_id": ""}


# --------------------------------------------------------------------------- #
# grammar + triggers
# --------------------------------------------------------------------------- #


@pytest.mark.faults
def test_spec_grammar_arg_and_trigger_fields_are_order_free():
    failpoints.configure("a.b:sleep:0.0:every=2,c.d:raise:msg")
    assert failpoints.has("a.b") and failpoints.has("c.d")
    with pytest.raises(failpoints.FailpointSpecError):
        failpoints._parse_entry("missing-action")
    with pytest.raises(failpoints.FailpointSpecError):
        failpoints._parse_entry("x.y:explode")
    with pytest.raises(failpoints.FailpointSpecError):
        failpoints._parse_entry("x.y:raise:bad=trigger")


@pytest.mark.faults
def test_hit_trigger_fires_exactly_once_on_the_nth_evaluation():
    failpoints.configure("p:fire:hit=3")
    assert [failpoints.failpoint("p") for _ in range(5)] == [None, None, True, None, None]
    assert failpoints.counts()["p"] == {"hits": 5, "fires": 1, "last_trace_id": ""}


@pytest.mark.faults
def test_every_trigger_fires_on_multiples():
    failpoints.configure("p:fire:every=2")
    assert [failpoints.failpoint("p") for _ in range(6)] == [None, True, None, True, None, True]


@pytest.mark.faults
def test_prob_trigger_is_deterministic_for_a_seed():
    failpoints.configure("p:fire:prob=0.5;seed=3")
    first = [failpoints.failpoint("p") for _ in range(16)]
    failpoints.configure("p:fire:prob=0.5;seed=3")
    assert [failpoints.failpoint("p") for _ in range(16)] == first
    assert any(first) and not all(first)


# --------------------------------------------------------------------------- #
# actions
# --------------------------------------------------------------------------- #


@pytest.mark.faults
def test_raise_action_raises_a_runtimeerror_subclass():
    failpoints.configure("p:raise:boom")
    with pytest.raises(failpoints.FailpointError, match="boom"):
        failpoints.failpoint("p")


@pytest.mark.faults
def test_drop_action_returns_the_sentinel():
    failpoints.configure("p:drop")
    assert failpoints.failpoint("p") is failpoints.DROPPED


@pytest.mark.faults
def test_corrupt_action_on_str_and_bytes_values():
    failpoints.configure("p:corrupt:2")
    s = failpoints.failpoint("p", value="hello world!")
    assert isinstance(s, str) and s != "hello world!"
    b = failpoints.failpoint("p", value=b"hello world!")
    assert isinstance(b, bytes) and b != b"hello world!" and len(b) == 12


@pytest.mark.faults
def test_corrupt_action_on_file_preserves_mtime(tmp_path):
    f = tmp_path / "blob.bin"
    f.write_bytes(b"A" * 64)
    before = os.stat(f)
    failpoints.configure("p:corrupt")
    assert failpoints.failpoint("p", path=str(f)) is True
    assert f.read_bytes() != b"A" * 64 and len(f.read_bytes()) == 64
    assert os.stat(f).st_mtime == before.st_mtime


@pytest.mark.faults
def test_truncate_action_tears_a_file(tmp_path):
    f = tmp_path / "blob.bin"
    f.write_bytes(b"A" * 100)
    failpoints.configure("p:truncate:0.25")
    failpoints.failpoint("p", path=str(f))
    assert len(f.read_bytes()) == 25


# --------------------------------------------------------------------------- #
# configuration surfaces
# --------------------------------------------------------------------------- #


@pytest.mark.faults
def test_env_configuration_and_reset():
    failpoints.configure_from_env({failpoints.ENV_VAR: "p:fire"})
    assert failpoints.enabled() and failpoints.failpoint("p") is True
    failpoints.configure_from_env({})
    assert not failpoints.enabled()


@pytest.mark.faults
def test_active_context_manager_restores_previous_registry():
    failpoints.configure("outer:fire")
    with failpoints.active("inner:drop"):
        assert failpoints.has("inner") and not failpoints.has("outer")
        assert failpoints.failpoint("inner") is failpoints.DROPPED
    assert failpoints.has("outer") and not failpoints.has("inner")
