import pickle

import numpy as np
import pytest

from sheeprl_tpu.utils.memmap import MemmapArray, is_shared


def test_create_and_write(tmp_path):
    arr = MemmapArray(shape=(4, 3), dtype=np.float32, filename=tmp_path / "a.memmap")
    arr[:] = np.ones((4, 3), dtype=np.float32)
    assert arr.shape == (4, 3)
    np.testing.assert_array_equal(np.asarray(arr), np.ones((4, 3)))
    assert is_shared(arr.array)


def test_temporary_file_cleanup():
    arr = MemmapArray(shape=(2,), dtype=np.float32)
    path = arr.filename
    assert path.exists()
    del arr
    assert not path.exists()


def test_from_array_copies(tmp_path):
    src = np.arange(6, dtype=np.int32).reshape(2, 3)
    mm = MemmapArray.from_array(src, filename=tmp_path / "b.memmap")
    np.testing.assert_array_equal(mm[:], src)
    src[0, 0] = 100
    assert mm[0, 0] == 0  # copied, not aliased


def test_ownership_not_transferred_same_file(tmp_path):
    a = MemmapArray(shape=(3,), dtype=np.float32, filename=tmp_path / "c.memmap")
    a[:] = 7
    b = MemmapArray.from_array(a, filename=tmp_path / "c.memmap")
    assert not b.has_ownership
    assert a.has_ownership
    np.testing.assert_array_equal(b[:], a[:])


def test_pickle_drops_ownership(tmp_path):
    a = MemmapArray(shape=(3,), dtype=np.float32, filename=tmp_path / "d.memmap")
    a[:] = 3
    b = pickle.loads(pickle.dumps(a))
    assert not b.has_ownership
    np.testing.assert_array_equal(b[:], np.full((3,), 3, dtype=np.float32))


def test_ndarray_ops(tmp_path):
    a = MemmapArray(shape=(3,), dtype=np.float32, filename=tmp_path / "e.memmap")
    a[:] = 2
    out = a + 1
    np.testing.assert_array_equal(out, np.full((3,), 3, dtype=np.float32))
    assert len(a) == 3


def test_set_array_wrong_size(tmp_path):
    a = MemmapArray(shape=(3,), dtype=np.float32, filename=tmp_path / "f.memmap")
    with pytest.raises(ValueError):
        a.array = np.zeros((10,), dtype=np.float32)
    with pytest.raises(ValueError):
        a.array = "nope"
