"""Health sentinel unit coverage: detector math (EWMA/z-score, streaks,
hysteresis), the response ladder, checkpoint certification gating, the
certification-aware keep_last GC, load_state's certified-first fallback, and
rollback digest parity (save -> certify -> take_rollback_state -> tree-equal)."""

import json
import math
import os

import numpy as np
import pytest

from sheeprl_tpu.core import health
from sheeprl_tpu.utils.checkpoint import (
    CheckpointCallback,
    certified_sidecar,
    certify,
    is_certified,
    latest_certified,
    load_state,
    save_state,
)
from sheeprl_tpu.utils.metric import EWMAStat


def _cfg(**over):
    """Minimal dict-config with the health group enabled + overrides."""
    group = {
        "enabled": True,
        "divergence": {"window": 16, "warmup": 4, "z_threshold": 6.0, "z_clear": 3.0, "streak": 2},
        "stall": {"enabled": False},
        "response": {"recover_iters": 3, "grace_iters": 2, "rollback_budget": 2},
    }

    def merge(dst, src):
        for k, v in src.items():
            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                merge(dst[k], v)
            else:
                dst[k] = v

    merge(group, over)
    return {"health": group}


# --------------------------------------------------------------------------- #
# EWMAStat
# --------------------------------------------------------------------------- #


def test_ewma_tracks_mean_and_variance():
    stat = EWMAStat(window=8)
    rng = np.random.default_rng(0)
    xs = rng.normal(5.0, 2.0, size=2000)
    for x in xs:
        stat.update(float(x))
    assert abs(stat.mean - 5.0) < 0.8
    assert abs(stat.std - 2.0) < 0.8


def test_ewma_zscore_flags_outliers_not_inliers():
    stat = EWMAStat(window=16)
    for _ in range(50):
        stat.update(1.0)
    assert abs(stat.zscore(1.0)) < 1.0
    assert abs(stat.zscore(1e6)) > 100.0


def test_ewma_ignores_nonfinite_and_zscore_is_inf():
    stat = EWMAStat(window=8)
    for _ in range(10):
        stat.update(2.0)
    mean_before = stat.mean
    stat.update(float("nan"))
    stat.update(float("inf"))
    assert stat.mean == mean_before  # non-finite samples never poison the moments
    assert math.isinf(stat.zscore(float("nan")))


def test_ewma_zscore_zero_until_two_samples():
    stat = EWMAStat(window=8)
    assert stat.zscore(123.0) == 0.0
    stat.update(1.0)
    assert stat.zscore(123.0) == 0.0
    stat.update(1.0)
    assert stat.zscore(123.0) != 0.0


# --------------------------------------------------------------------------- #
# Detectors
# --------------------------------------------------------------------------- #


def test_divergence_quiet_on_stationary_signal():
    det = health.DivergenceDetector(warmup=4, streak=2)
    rng = np.random.default_rng(1)
    for x in rng.normal(0.5, 0.01, size=200):
        fired, _ = det.check({"Loss/value_loss": float(x)})
        assert not fired


def test_divergence_fires_after_streak_not_single_blip():
    det = health.DivergenceDetector(warmup=4, z_threshold=6.0, streak=3)
    for _ in range(20):
        det.check({"Loss/value_loss": 1.0})
    fired, _ = det.check({"Loss/value_loss": 1e4})
    assert not fired  # streak 1 of 3
    fired, _ = det.check({"Loss/value_loss": 1e4})
    assert not fired
    fired, reason = det.check({"Loss/value_loss": 1e4})
    assert fired and "Loss/value_loss" in reason


def test_divergence_anomalous_samples_do_not_move_baseline():
    det = health.DivergenceDetector(warmup=4, streak=100)  # huge streak: never fires
    for _ in range(20):
        det.check({"k": 1.0})
    baseline = det._stats["k"].mean
    for _ in range(50):
        det.check({"k": 1e4})
    assert det._stats["k"].mean == baseline


def test_divergence_nan_is_immediate_anomaly():
    det = health.DivergenceDetector(warmup=4, streak=1)
    fired, reason = det.check({"k": float("nan")})
    assert fired and "inf" in reason


def test_divergence_hysteresis_z_clear_keeps_episode_open():
    det = health.DivergenceDetector(warmup=4, z_threshold=8.0, z_clear=3.0, streak=1)
    rng = np.random.default_rng(2)
    for x in rng.normal(0.0, 1.0, size=100):
        det.check({"k": float(x)})
    std = max(det._stats["k"].std, 1e-8)
    mean = det._stats["k"].mean
    det.check({"k": mean + 20 * std})  # open the episode (z > 8)
    assert det._in_anomaly["k"]
    det.check({"k": mean + 5 * std})  # 3 < z < 8: stays OPEN under hysteresis
    assert det._in_anomaly["k"]
    det.check({"k": mean})  # back under z_clear: closes
    assert not det._in_anomaly["k"]


def test_stall_detector_fires_on_sps_collapse():
    det = health.StallDetector(warmup=4, floor_ratio=0.2, streak=2)
    for _ in range(10):
        fired, _ = det.check(steps=1000.0, elapsed_s=1.0)
        assert not fired
    fired, _ = det.check(steps=10.0, elapsed_s=1.0)
    assert not fired  # streak 1 of 2
    fired, reason = det.check(steps=10.0, elapsed_s=1.0)
    assert fired and "stall" in reason


def test_stall_detector_deadline():
    det = health.StallDetector(warmup=2, deadline_s=0.5)
    fired, reason = det.check(steps=100.0, elapsed_s=2.0)
    assert fired and "deadline" in reason


def test_thrash_detector_skip_and_retrace_streaks():
    det = health.ThrashDetector(skip_streak=3, retrace_streak=2)
    assert not det.check(skipped=1, retraces=0)[0]
    assert not det.check(skipped=1, retraces=0)[0]
    assert det.check(skipped=1, retraces=0)[0]
    det.reset()
    assert not det.check(skipped=0, retraces=1)[0]
    fired, reason = det.check(skipped=0, retraces=1)
    assert fired and "retrace" in reason
    # a clean check resets both streaks
    det.reset()
    det.check(skipped=1, retraces=0)
    det.check(skipped=0, retraces=0)
    assert not det.check(skipped=1, retraces=0)[0]


# --------------------------------------------------------------------------- #
# Sentinel ladder
# --------------------------------------------------------------------------- #


def _feed_healthy(sentinel, n, start=0, step=64):
    for i in range(n):
        action = sentinel.observe(start + i * step, train_metrics={"Loss/value_loss": 1.0})
        assert action.kind == "none"
    return start + n * step


def test_sentinel_disabled_is_noop(tmp_path):
    sentinel = health.HealthSentinel({}, log_dir=str(tmp_path))
    action = sentinel.observe(0, train_metrics={"Loss/value_loss": float("nan")})
    assert action is health.NO_ACTION
    assert sentinel.lr_scale == 1.0
    assert not sentinel.certifiable  # disabled runs never certify
    assert not os.path.exists(tmp_path / "health")


def test_sentinel_ladder_escalates_and_backs_off(tmp_path):
    sentinel = health.HealthSentinel(_cfg(), log_dir=str(tmp_path))
    step = _feed_healthy(sentinel, 20)
    kinds = []
    for i in range(4):
        a = sentinel.observe(step + i * 64, train_metrics={"Loss/value_loss": 1e6})
        kinds.append(a.kind)
    # streak=2 delays the first detection one check; then warn -> backoff -> rollback
    assert kinds == ["none", "warn", "backoff", "rollback"]
    assert sentinel.lr_scale == pytest.approx(0.5)
    assert not sentinel.certifiable  # open anomaly episode blocks certification
    events = [
        json.loads(l)
        for l in open(tmp_path / "health" / "events.jsonl").read().splitlines()
    ]
    assert [e["event"] for e in events] == ["warn", "backoff", "rollback_requested"]
    # flight recorder flushed on each ladder action
    assert len(list((tmp_path / "health").glob("flight_*.jsonl"))) == 3


def test_sentinel_recovers_after_healthy_streak(tmp_path):
    sentinel = health.HealthSentinel(_cfg(), log_dir=str(tmp_path))
    step = _feed_healthy(sentinel, 20)
    sentinel.observe(step, train_metrics={"Loss/value_loss": 1e6})
    sentinel.observe(step + 64, train_metrics={"Loss/value_loss": 1e6})  # warn
    assert sentinel._level == 1
    _feed_healthy(sentinel, 5, start=step + 128)  # recover_iters=3
    assert sentinel._level == 0 and sentinel.lr_scale == 1.0
    assert sentinel.certifiable


def test_sentinel_supports_filters_ladder(tmp_path):
    sentinel = health.HealthSentinel(_cfg(), log_dir=str(tmp_path), supports=("warn",))
    step = _feed_healthy(sentinel, 20)
    kinds = [
        sentinel.observe(step + i * 64, train_metrics={"Loss/value_loss": 1e6}).kind
        for i in range(5)
    ]
    assert set(kinds) <= {"none", "warn"}  # backoff/rollback fall back to warn
    assert sentinel.lr_scale == 1.0


def test_sentinel_counters_drain_deltas(tmp_path):
    class Agg:
        def __init__(self):
            self.seen = {}

        def __contains__(self, k):
            return True

        def update(self, k, v):
            self.seen[k] = self.seen.get(k, 0) + v

    sentinel = health.HealthSentinel(_cfg(), log_dir=str(tmp_path))
    step = _feed_healthy(sentinel, 20)
    for i in range(3):
        sentinel.observe(step + i * 64, train_metrics={"Loss/value_loss": 1e6})
    agg = Agg()
    sentinel.drain(agg)
    assert agg.seen["Health/detections"] == 2  # streak=2 eats the first check
    assert agg.seen["Health/warns"] == 1
    assert agg.seen["Health/backoffs"] == 1
    first = dict(agg.seen)
    sentinel.drain(agg)  # no new events: counters must NOT double-count
    assert agg.seen["Health/detections"] == first["Health/detections"]


# --------------------------------------------------------------------------- #
# Certification + GC + load_state preference
# --------------------------------------------------------------------------- #


def _write_ckpt(path, iter_num, mtime):
    save_state(str(path), {"iter_num": iter_num, "agent": np.full((3,), iter_num, np.float32)})
    os.utime(path, (mtime, mtime))


def _corrupt(path):
    st = path.stat()
    raw = bytearray(path.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    path.write_bytes(bytes(raw))
    os.utime(path, (st.st_atime, st.st_mtime))


def test_certify_roundtrip_and_size_guard(tmp_path):
    p = tmp_path / "ckpt_10_0.ckpt"
    info = save_state(str(p), {"iter_num": 10})
    assert not is_certified(str(p))
    certify(str(p), crc32=info["crc32"], size=info["size"], policy_step=10)
    assert is_certified(str(p))
    payload = json.loads(open(certified_sidecar(str(p))).read())
    assert payload["policy_step"] == 10 and payload["crc32"] == info["crc32"]
    # overwriting the checkpoint after certification voids the sidecar
    save_state(str(p), {"iter_num": 11, "pad": np.zeros(64, np.float32)})
    assert not is_certified(str(p))


def test_checkpoint_callback_certifies_only_when_healthy(tmp_path):
    cb = CheckpointCallback()
    good = tmp_path / "ckpt_10_0.ckpt"
    bad = tmp_path / "ckpt_20_0.ckpt"
    cb.on_checkpoint_coupled(None, str(good), {"iter_num": 10}, healthy=True, policy_step=10)
    cb.on_checkpoint_coupled(None, str(bad), {"iter_num": 20}, healthy=False, policy_step=20)
    assert is_certified(str(good))
    assert not os.path.exists(certified_sidecar(str(bad)))
    # healthy=None (loop without a sentinel): no sidecar either
    legacy = tmp_path / "ckpt_30_0.ckpt"
    cb.on_checkpoint_coupled(None, str(legacy), {"iter_num": 30})
    assert not os.path.exists(certified_sidecar(str(legacy)))


def test_gc_exempts_certified_from_main_window(tmp_path):
    cb = CheckpointCallback(keep_last=1)
    cert = tmp_path / "ckpt_10_0.ckpt"
    info = save_state(str(cert), {"iter_num": 10})
    os.utime(cert, (1000, 1000))
    certify(str(cert), crc32=info["crc32"], size=info["size"])
    _write_ckpt(tmp_path / "ckpt_20_0.ckpt", 20, 2000)
    _write_ckpt(tmp_path / "ckpt_30_0.ckpt", 30, 3000)
    cb._gc(str(tmp_path))
    names = sorted(f.name for f in tmp_path.glob("ckpt_*.ckpt"))
    # the certified OLDEST survives keep_last=1; the newest plain survives too
    assert names == ["ckpt_10_0.ckpt", "ckpt_30_0.ckpt"]
    assert is_certified(str(cert))


def test_gc_ages_out_certified_under_own_budget(tmp_path):
    cb = CheckpointCallback(keep_last=1)
    for step, mtime in ((10, 1000), (20, 2000), (30, 3000)):
        p = tmp_path / f"ckpt_{step}_0.ckpt"
        info = save_state(str(p), {"iter_num": step})
        os.utime(p, (mtime, mtime))
        certify(str(p), crc32=info["crc32"], size=info["size"])
    cb._gc(str(tmp_path))
    assert sorted(f.name for f in tmp_path.glob("ckpt_*.ckpt")) == ["ckpt_30_0.ckpt"]
    # sidecars of the aged-out certified files went with them
    assert sorted(f.name for f in tmp_path.glob("*.certified.json")) == [
        "ckpt_30_0.ckpt.certified.json"
    ]


def test_gc_sweeps_orphan_sidecars(tmp_path):
    cb = CheckpointCallback(keep_last=2)
    _write_ckpt(tmp_path / "ckpt_10_0.ckpt", 10, 1000)
    orphan = tmp_path / "ckpt_99_0.ckpt.certified.json"
    orphan.write_text(json.dumps({"certified": True, "ckpt": "ckpt_99_0.ckpt"}))
    cb._gc(str(tmp_path))
    assert not orphan.exists()


def test_latest_certified_picks_newest_by_mtime(tmp_path):
    assert latest_certified(str(tmp_path)) is None
    for step, mtime in ((10, 1000), (20, 2000)):
        p = tmp_path / f"ckpt_{step}_0.ckpt"
        info = save_state(str(p), {"iter_num": step})
        os.utime(p, (mtime, mtime))
        certify(str(p), crc32=info["crc32"], size=info["size"])
    _write_ckpt(tmp_path / "ckpt_30_0.ckpt", 30, 3000)  # newest but NOT certified
    assert latest_certified(str(tmp_path)).endswith("ckpt_20_0.ckpt")


def test_load_state_fallback_prefers_certified_sibling(tmp_path):
    # newest corrupt; among the older siblings the CERTIFIED one wins even
    # though a newer non-certified sibling exists
    cert = tmp_path / "ckpt_10_0.ckpt"
    info = save_state(str(cert), {"iter_num": 10, "agent": np.zeros(3, np.float32)})
    os.utime(cert, (1000, 1000))
    certify(str(cert), crc32=info["crc32"], size=info["size"])
    _write_ckpt(tmp_path / "ckpt_20_0.ckpt", 20, 2000)
    newest = tmp_path / "ckpt_30_0.ckpt"
    _write_ckpt(newest, 30, 3000)
    _corrupt(newest)
    with pytest.warns(UserWarning, match="older sibling"):
        state = load_state(str(newest))
    assert state["iter_num"] == 10


# --------------------------------------------------------------------------- #
# Rollback
# --------------------------------------------------------------------------- #


def _armed_sentinel(tmp_path, **over):
    sentinel = health.HealthSentinel(_cfg(**over), log_dir=str(tmp_path))
    _feed_healthy(sentinel, 20)
    return sentinel


def test_rollback_digest_parity(tmp_path):
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    state = {
        "agent": {"w": np.arange(12, dtype=np.float32).reshape(3, 4), "b": np.ones(4, np.float32)},
        "iter_num": 7,
    }
    p = ckpt_dir / "ckpt_7_0.ckpt"
    info = save_state(str(p), state)
    certify(str(p), crc32=info["crc32"], size=info["size"])
    sentinel = _armed_sentinel(tmp_path)
    restored = sentinel.take_rollback_state(str(ckpt_dir))
    assert restored is not None
    np.testing.assert_array_equal(restored["agent"]["w"], state["agent"]["w"])
    np.testing.assert_array_equal(restored["agent"]["b"], state["agent"]["b"])
    assert restored["iter_num"] == 7
    # post-rollback: detectors reset, grace window armed, scale tightened
    assert sentinel._grace == 2 and sentinel.lr_scale == pytest.approx(0.5)
    events = [
        json.loads(l)
        for l in open(tmp_path / "health" / "events.jsonl").read().splitlines()
    ]
    assert events[-1]["event"] == "rollback"
    assert events[-1]["path"].endswith("ckpt_7_0.ckpt")


def test_rollback_refuses_uncertified(tmp_path):
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    _write_ckpt(ckpt_dir / "ckpt_7_0.ckpt", 7, 1000)  # present but never certified
    sentinel = _armed_sentinel(tmp_path)
    assert sentinel.take_rollback_state(str(ckpt_dir)) is None


def test_rollback_budget_is_bounded(tmp_path):
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    p = ckpt_dir / "ckpt_7_0.ckpt"
    info = save_state(str(p), {"iter_num": 7})
    certify(str(p), crc32=info["crc32"], size=info["size"])
    sentinel = _armed_sentinel(tmp_path, response={"rollback_budget": 1, "grace_iters": 0})
    assert sentinel.take_rollback_state(str(ckpt_dir)) is not None
    assert sentinel.take_rollback_state(str(ckpt_dir)) is None  # budget spent


def test_grace_window_suppresses_detection(tmp_path):
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    p = ckpt_dir / "ckpt_7_0.ckpt"
    info = save_state(str(p), {"iter_num": 7})
    certify(str(p), crc32=info["crc32"], size=info["size"])
    sentinel = _armed_sentinel(tmp_path)
    assert sentinel.take_rollback_state(str(ckpt_dir)) is not None
    assert not sentinel.certifiable  # never certify inside the grace window
    # grace_iters=2: the next two observes ignore even NaN losses
    a1 = sentinel.observe(10_000, train_metrics={"Loss/value_loss": float("nan")})
    assert not sentinel.certifiable  # still one grace check left
    a2 = sentinel.observe(10_064, train_metrics={"Loss/value_loss": float("nan")})
    assert a1.kind == "none" and a2.kind == "none"


def test_resolve_tolerates_missing_group():
    view = health.resolve({})
    assert view.enabled is False
    assert view.divergence.z_threshold == 8.0
    view2 = health.resolve({"health": {"enabled": True}})
    assert view2.enabled is True
    assert view2.response.ladder == ["warn", "backoff", "rollback"]
