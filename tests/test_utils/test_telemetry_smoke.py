"""Satellite registration of scripts/telemetry_smoke.py as a tier-1 test: a
serve process launched with a parent-pinned ``SHEEPRL_TPU_TRACE`` id and a
one-shot reload-canary failpoint must surface that SINGLE trace id in the
Prometheus ``{"op": "metrics"}`` exposition, the ``serve_reload_rollback``
row of ``<run_dir>/health/events.jsonl``, and the metadata + spans of the
Perfetto export written at shutdown (full harness, fresh interpreter)."""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.telemetry
@pytest.mark.timeout(300)
def test_telemetry_smoke_one_trace_id_across_all_surfaces(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "telemetry_smoke.py"),
            "--workdir",
            str(tmp_path),
            "--timeout",
            "240",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=280,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-2500:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "telemetry smoke OK" in out.stdout
    # the drill's own assertions already ran; independently re-join the id
    # across the three artifacts it leaves behind
    with open(tmp_path / "stats.json") as f:
        stats = json.load(f)
    trace_id = stats["trace_id"]
    assert trace_id and stats["Serve/ok"] > 0
    with open(stats["trace_path"]) as f:
        doc = json.load(f)
    assert doc["metadata"]["trace_id"] == trace_id
    assert any(ev.get("name") == "serve/request" for ev in doc["traceEvents"])
    events_path = tmp_path / "run" / "health" / "events.jsonl"
    rows = [json.loads(ln) for ln in events_path.read_text().splitlines()]
    rollbacks = [r for r in rows if r["event"] == "serve_reload_rollback"]
    assert rollbacks and all(r["trace_id"] == trace_id for r in rollbacks)
