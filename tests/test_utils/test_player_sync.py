"""Unit tests for the host-player plumbing: PlayerParamsSync, Runtime.player_device,
and the TraceProfiler window logic."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.core.runtime import Runtime
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.utils import PlayerParamsSync


def _params(scale=1.0):
    return {
        "enc": {"w": jnp.full((8, 16), scale), "b": jnp.zeros((16,))},
        "head": {"w": jnp.full((16, 4), 2 * scale)},
    }


def test_player_params_sync_roundtrip():
    rt = Runtime(accelerator="cpu", devices=2)
    params = rt.replicate(_params())
    sync = PlayerParamsSync(rt.to_player(params))
    flat = jax.jit(sync.ravel)(params)
    assert flat.ndim == 1 and flat.size == 8 * 16 + 16 + 16 * 4
    pulled = sync.pull(flat, rt.player_device)
    for (ka, va), (kb, vb) in zip(
        jax.tree_util.tree_leaves_with_path(pulled), jax.tree_util.tree_leaves_with_path(params)
    ):
        assert ka == kb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    # committed to the player device
    assert next(iter(jax.tree_util.tree_leaves(pulled))).devices() == {rt.player_device}


def test_player_params_sync_tracks_updates():
    rt = Runtime(accelerator="cpu", devices=1)
    sync = PlayerParamsSync(_params())
    ravel_jit = jax.jit(sync.ravel)
    for scale in (1.0, -3.0, 0.25):
        pulled = sync.pull(ravel_jit(_params(scale)), rt.player_device)
        np.testing.assert_allclose(np.asarray(pulled["head"]["w"]), 2 * scale)


def test_player_device_selection(monkeypatch):
    on_host = Runtime(accelerator="cpu", devices=2, player_on_host=True)
    on_mesh = Runtime(accelerator="cpu", devices=2, player_on_host=False)
    # On the CPU-only test mesh host_device == mesh device 0, which would make
    # the assertions tautological; pretend the host CPU is a DIFFERENT device so
    # the player_on_host branch is actually discriminated.
    fake_host = jax.devices("cpu")[1]
    real_local_devices = jax.local_devices

    def fake_local_devices(process_index=None, backend=None, host_id=None):
        if backend == "cpu":
            return [fake_host]
        return real_local_devices(process_index=process_index, backend=backend, host_id=host_id)

    monkeypatch.setattr(jax, "local_devices", fake_local_devices)
    assert on_host.player_device == fake_host
    assert on_host.player_device != on_host.device
    assert on_mesh.player_device == on_mesh.device
    assert on_mesh.player_device != fake_host


class _FakePlayer:
    wm_params = None
    actor_params = None


def _dreamer_params(scale=1.0):
    return {
        "world_model": {
            "encoder": {"w": jnp.full((4, 8), scale)},
            "recurrent_model": {"w": jnp.full((8, 8), 2 * scale)},
            "representation_model": {"w": jnp.full((8, 4), 3 * scale)},
            "observation_model": {"w": jnp.full((4, 4), 99.0)},  # player never needs this
            "reward_model": {"w": jnp.full((4, 1), 98.0)},
        },
        "actor": {"w": jnp.full((8, 2), 4 * scale)},
        "critic": {"w": jnp.full((8, 1), 97.0)},
    }


def test_dreamer_player_sync_host_player():
    from sheeprl_tpu.utils.utils import DreamerPlayerSync

    rt = Runtime(accelerator="cpu", devices=2, player_on_host=True)
    keys = ("encoder", "recurrent_model", "representation_model")
    params = rt.replicate(_dreamer_params())
    psync = DreamerPlayerSync(rt, params, wm_keys=keys, every=1)
    player = _FakePlayer()

    psync.push(player, params, force=True)
    # only the player subset ships; decoder/reward/critic stay behind
    assert set(player.wm_params) == set(keys)
    np.testing.assert_allclose(np.asarray(player.actor_params["w"]), 4.0)
    leaf = player.wm_params["encoder"]["w"]
    assert leaf.devices() == {rt.player_device}

    # every=1: the train step's in-graph ravel output drives the refresh
    new = rt.replicate(_dreamer_params(scale=2.0))
    flat = jax.jit(psync.ravel)(new)
    assert flat is not None and flat.ndim == 1
    psync.push(player, new, flat=flat)
    np.testing.assert_allclose(np.asarray(player.wm_params["representation_model"]["w"]), 6.0)
    np.testing.assert_allclose(np.asarray(player.actor_params["w"]), 8.0)


def test_dreamer_player_sync_cadence():
    from sheeprl_tpu.utils.utils import DreamerPlayerSync

    rt = Runtime(accelerator="cpu", devices=1, player_on_host=True)
    keys = ("encoder", "recurrent_model", "representation_model")
    psync = DreamerPlayerSync(rt, _dreamer_params(), wm_keys=keys, every=3)
    # with a >1 cadence the per-train in-graph ravel is skipped entirely
    assert psync.ravel(_dreamer_params()) is None
    player = _FakePlayer()
    psync.push(player, _dreamer_params(), force=True)

    stale = np.asarray(player.actor_params["w"]).copy()
    psync.push(player, _dreamer_params(5.0))  # call 1 of 3: stale
    psync.push(player, _dreamer_params(6.0))  # call 2 of 3: stale
    np.testing.assert_allclose(np.asarray(player.actor_params["w"]), stale)
    psync.push(player, _dreamer_params(7.0))  # cadence hit: refreshed
    np.testing.assert_allclose(np.asarray(player.actor_params["w"]), 28.0)


def test_dreamer_player_sync_mesh_player_rebinds():
    from sheeprl_tpu.utils.utils import DreamerPlayerSync

    rt = Runtime(accelerator="cpu", devices=2, player_on_host=False)
    params = rt.replicate(_dreamer_params())
    psync = DreamerPlayerSync(rt, params, wm_keys=("encoder",), every=1)
    assert psync.ravel(params) is None  # no transfer machinery on the mesh path
    player = _FakePlayer()
    psync.push(player, params, force=True)
    # mesh path rebinds the full world model by reference (pre-r5 behavior)
    assert player.wm_params is params["world_model"]
    assert player.actor_params is params["actor"]


def test_trace_profiler_window(monkeypatch, tmp_path):
    calls = []
    import jax.profiler as jp

    monkeypatch.setattr(jp, "start_trace", lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jp, "stop_trace", lambda: calls.append(("stop",)))
    prof = TraceProfiler({"enabled": True, "start_step": 100, "num_iters": 3}, str(tmp_path))
    for step in (0, 50, 99):
        prof.step(step)
    assert calls == []
    prof.step(100)  # starts
    assert calls and calls[0][0] == "start"
    prof.step(110)
    prof.step(120)
    prof.step(130)  # third counted iteration -> stop
    assert calls[-1] == ("stop",)
    n_calls = len(calls)
    prof.step(140)  # done: no restart
    prof.close()  # idempotent
    assert len(calls) == n_calls


def test_trace_profiler_disabled_noop(tmp_path):
    prof = TraceProfiler({"enabled": False}, str(tmp_path))
    prof.step(0)
    prof.close()
    prof = TraceProfiler({"enabled": True}, None)  # non-zero rank: no log dir
    prof.step(0)
    prof.close()
