"""Unit tests for the host-player plumbing: PlayerParamsSync, Runtime.player_device,
and the TraceProfiler window logic."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.core.runtime import Runtime
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.utils import PlayerParamsSync


def _params(scale=1.0):
    return {
        "enc": {"w": jnp.full((8, 16), scale), "b": jnp.zeros((16,))},
        "head": {"w": jnp.full((16, 4), 2 * scale)},
    }


def test_player_params_sync_roundtrip():
    rt = Runtime(accelerator="cpu", devices=2)
    params = rt.replicate(_params())
    sync = PlayerParamsSync(rt.to_player(params))
    flat = jax.jit(sync.ravel)(params)
    assert flat.ndim == 1 and flat.size == 8 * 16 + 16 + 16 * 4
    pulled = sync.pull(flat, rt.player_device)
    for (ka, va), (kb, vb) in zip(
        jax.tree_util.tree_leaves_with_path(pulled), jax.tree_util.tree_leaves_with_path(params)
    ):
        assert ka == kb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    # committed to the player device
    assert next(iter(jax.tree_util.tree_leaves(pulled))).devices() == {rt.player_device}


def test_player_params_sync_tracks_updates():
    rt = Runtime(accelerator="cpu", devices=1)
    sync = PlayerParamsSync(_params())
    ravel_jit = jax.jit(sync.ravel)
    for scale in (1.0, -3.0, 0.25):
        pulled = sync.pull(ravel_jit(_params(scale)), rt.player_device)
        np.testing.assert_allclose(np.asarray(pulled["head"]["w"]), 2 * scale)


def test_player_device_selection(monkeypatch):
    on_host = Runtime(accelerator="cpu", devices=2, player_on_host=True)
    on_mesh = Runtime(accelerator="cpu", devices=2, player_on_host=False)
    # On the CPU-only test mesh host_device == mesh device 0, which would make
    # the assertions tautological; pretend the host CPU is a DIFFERENT device so
    # the player_on_host branch is actually discriminated.
    fake_host = jax.devices("cpu")[1]
    real_local_devices = jax.local_devices

    def fake_local_devices(process_index=None, backend=None, host_id=None):
        if backend == "cpu":
            return [fake_host]
        return real_local_devices(process_index=process_index, backend=backend, host_id=host_id)

    monkeypatch.setattr(jax, "local_devices", fake_local_devices)
    assert on_host.player_device == fake_host
    assert on_host.player_device != on_host.device
    assert on_mesh.player_device == on_mesh.device
    assert on_mesh.player_device != fake_host


def test_trace_profiler_window(monkeypatch, tmp_path):
    calls = []
    import jax.profiler as jp

    monkeypatch.setattr(jp, "start_trace", lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jp, "stop_trace", lambda: calls.append(("stop",)))
    prof = TraceProfiler({"enabled": True, "start_step": 100, "num_iters": 3}, str(tmp_path))
    for step in (0, 50, 99):
        prof.step(step)
    assert calls == []
    prof.step(100)  # starts
    assert calls and calls[0][0] == "start"
    prof.step(110)
    prof.step(120)
    prof.step(130)  # third counted iteration -> stop
    assert calls[-1] == ("stop",)
    n_calls = len(calls)
    prof.step(140)  # done: no restart
    prof.close()  # idempotent
    assert len(calls) == n_calls


def test_trace_profiler_disabled_noop(tmp_path):
    prof = TraceProfiler({"enabled": False}, str(tmp_path))
    prof.step(0)
    prof.close()
    prof = TraceProfiler({"enabled": True}, None)  # non-zero rank: no log dir
    prof.step(0)
    prof.close()
