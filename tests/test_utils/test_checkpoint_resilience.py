"""Durability half of the checkpoint layer: corruption fallback to an older
sibling checkpoint and the CheckpointCallback keep_last garbage collection
(in-flight ``.tmp`` writes must never count against the retention budget)."""

import os

import numpy as np
import pytest

from sheeprl_tpu.utils.checkpoint import (
    CheckpointCallback,
    CheckpointCorruptionError,
    load_state,
    save_state,
)


def _write_ckpt(path, iter_num, mtime):
    save_state(str(path), {"iter_num": iter_num, "agent": np.full((3,), iter_num, np.float32)})
    os.utime(path, (mtime, mtime))


def _corrupt(path):
    st = path.stat()
    raw = bytearray(path.read_bytes())
    raw[len(raw) // 2] ^= 0xFF  # flip a byte inside the CRC-covered state pickle
    path.write_bytes(bytes(raw))
    os.utime(path, (st.st_atime, st.st_mtime))  # keep the sibling mtime ordering


def test_fallback_to_newest_older_sibling(tmp_path):
    _write_ckpt(tmp_path / "ckpt_10_0.ckpt", 10, 1000)
    _write_ckpt(tmp_path / "ckpt_20_0.ckpt", 20, 2000)
    newest = tmp_path / "ckpt_30_0.ckpt"
    _write_ckpt(newest, 30, 3000)
    _corrupt(newest)
    with pytest.warns(UserWarning, match="older sibling"):
        state = load_state(str(newest))
    # the NEWEST older sibling, not just any: one checkpoint interval lost
    assert state["iter_num"] == 20


def test_fallback_skips_corrupt_siblings(tmp_path):
    _write_ckpt(tmp_path / "ckpt_10_0.ckpt", 10, 1000)
    mid = tmp_path / "ckpt_20_0.ckpt"
    _write_ckpt(mid, 20, 2000)
    newest = tmp_path / "ckpt_30_0.ckpt"
    _write_ckpt(newest, 30, 3000)
    _corrupt(newest)
    _corrupt(mid)  # the first fallback candidate is ALSO torn
    with pytest.warns(UserWarning, match="older sibling"):
        state = load_state(str(newest))
    assert state["iter_num"] == 10


def test_fallback_ignores_newer_siblings_and_non_ckpt_files(tmp_path):
    corrupt = tmp_path / "ckpt_10_0.ckpt"
    _write_ckpt(corrupt, 10, 1000)
    _corrupt(corrupt)
    # a NEWER sibling is a different (later) run state — resuming from it would
    # silently jump the run forward, so it must not be a fallback candidate
    _write_ckpt(tmp_path / "ckpt_20_0.ckpt", 20, 2000)
    (tmp_path / "notes.txt").write_text("not a checkpoint")
    with pytest.raises(CheckpointCorruptionError):
        load_state(str(corrupt))


def test_fallback_can_be_disabled(tmp_path):
    _write_ckpt(tmp_path / "ckpt_10_0.ckpt", 10, 1000)
    newest = tmp_path / "ckpt_20_0.ckpt"
    _write_ckpt(newest, 20, 2000)
    _corrupt(newest)
    with pytest.raises(CheckpointCorruptionError, match="integrity|unreadable|corrupt"):
        load_state(str(newest), fallback_to_older=False)


def test_gc_keep_last_prunes_oldest_and_never_counts_tmp(tmp_path):
    for i, mtime in [(1, 1000), (2, 2000), (3, 3000), (4, 4000)]:
        p = tmp_path / f"ckpt_{i}_0.ckpt"
        p.write_bytes(b"x")
        os.utime(p, (mtime, mtime))
    # an in-flight atomic write: must neither count toward keep_last nor be removed
    tmp = tmp_path / "ckpt_5_0.ckpt.tmp"
    tmp.write_bytes(b"partial")
    os.utime(tmp, (500, 500))  # even as the oldest file in the dir

    CheckpointCallback(keep_last=2)._gc(str(tmp_path))
    assert sorted(os.listdir(tmp_path)) == [
        "ckpt_3_0.ckpt",
        "ckpt_4_0.ckpt",
        "ckpt_5_0.ckpt.tmp",
    ]


def test_gc_disabled_keeps_everything(tmp_path):
    for i in range(3):
        (tmp_path / f"ckpt_{i}_0.ckpt").write_bytes(b"x")
    CheckpointCallback(keep_last=None)._gc(str(tmp_path))
    CheckpointCallback(keep_last=0)._gc(str(tmp_path))
    assert len(list(tmp_path.glob("*.ckpt"))) == 3
    # a vanished directory is a no-op, not a crash
    CheckpointCallback(keep_last=2)._gc(str(tmp_path / "missing"))
