"""Durability half of the checkpoint layer: corruption fallback to an older
sibling checkpoint and the CheckpointCallback keep_last garbage collection
(in-flight ``.tmp`` writes must never count against the retention budget).
Faults are injected through the core/failpoints.py registry — the same drill
sites (ckpt.pre_fsync / ckpt.finalize / ckpt.load) operators use in prod."""

import os

import numpy as np
import pytest

from sheeprl_tpu.core import failpoints
from sheeprl_tpu.utils.checkpoint import (
    CheckpointCallback,
    CheckpointCorruptionError,
    certified_under,
    certify,
    is_certified,
    latest_certified,
    load_state,
    read_footer_crc,
    save_state,
)


def _write_ckpt(path, iter_num, mtime):
    save_state(str(path), {"iter_num": iter_num, "agent": np.full((3,), iter_num, np.float32)})
    os.utime(path, (mtime, mtime))


def _corrupt(path):
    """Registry-driven file corruption — the `corrupt` failpoint action flips
    bytes inside the CRC-covered state pickle and preserves the mtime (so the
    sibling ordering survives), exactly what `ckpt.finalize:corrupt` does to a
    live run. No hand-rolled byte flipper."""
    with failpoints.active("drill.corrupt_file:corrupt"):
        assert failpoints.failpoint("drill.corrupt_file", path=str(path)) is True


@pytest.mark.faults
def test_fallback_to_newest_older_sibling(tmp_path):
    _write_ckpt(tmp_path / "ckpt_10_0.ckpt", 10, 1000)
    _write_ckpt(tmp_path / "ckpt_20_0.ckpt", 20, 2000)
    newest = tmp_path / "ckpt_30_0.ckpt"
    _write_ckpt(newest, 30, 3000)
    _corrupt(newest)
    with pytest.warns(UserWarning, match="older sibling"):
        state = load_state(str(newest))
    # the NEWEST older sibling, not just any: one checkpoint interval lost
    assert state["iter_num"] == 20


@pytest.mark.faults
def test_fallback_skips_corrupt_siblings(tmp_path):
    _write_ckpt(tmp_path / "ckpt_10_0.ckpt", 10, 1000)
    mid = tmp_path / "ckpt_20_0.ckpt"
    _write_ckpt(mid, 20, 2000)
    newest = tmp_path / "ckpt_30_0.ckpt"
    _write_ckpt(newest, 30, 3000)
    _corrupt(newest)
    _corrupt(mid)  # the first fallback candidate is ALSO torn
    with pytest.warns(UserWarning, match="older sibling"):
        state = load_state(str(newest))
    assert state["iter_num"] == 10


@pytest.mark.faults
def test_fallback_ignores_newer_siblings_and_non_ckpt_files(tmp_path):
    corrupt = tmp_path / "ckpt_10_0.ckpt"
    _write_ckpt(corrupt, 10, 1000)
    _corrupt(corrupt)
    # a NEWER sibling is a different (later) run state — resuming from it would
    # silently jump the run forward, so it must not be a fallback candidate
    _write_ckpt(tmp_path / "ckpt_20_0.ckpt", 20, 2000)
    (tmp_path / "notes.txt").write_text("not a checkpoint")
    with pytest.raises(CheckpointCorruptionError):
        load_state(str(corrupt))


@pytest.mark.faults
def test_fallback_can_be_disabled(tmp_path):
    _write_ckpt(tmp_path / "ckpt_10_0.ckpt", 10, 1000)
    newest = tmp_path / "ckpt_20_0.ckpt"
    _write_ckpt(newest, 20, 2000)
    _corrupt(newest)
    with pytest.raises(CheckpointCorruptionError, match="integrity|unreadable|corrupt"):
        load_state(str(newest), fallback_to_older=False)


@pytest.mark.faults
def test_torn_write_before_fsync_is_detected_and_falls_back(tmp_path):
    """A write torn between flush and fsync (power loss mid-durability): the
    truncated file reaches the final name, the CRC footer is gone, and resume
    must fall back to the intact older sibling."""
    _write_ckpt(tmp_path / "ckpt_10_0.ckpt", 10, 1000)
    newest = tmp_path / "ckpt_20_0.ckpt"
    with failpoints.active("ckpt.pre_fsync:truncate:0.5"):
        save_state(str(newest), {"iter_num": 20, "agent": np.full((3,), 20, np.float32)})
    os.utime(newest, (2000, 2000))
    with pytest.warns(UserWarning, match="older sibling"):
        state = load_state(str(newest))
    assert state["iter_num"] == 10


@pytest.mark.faults
def test_crash_before_fsync_leaves_previous_checkpoint_intact(tmp_path):
    """A crash BEFORE durability (raise at the pre-fsync drill site): the
    atomic-rename protocol must leave the previous checkpoint untouched under
    the final name — the failed overwrite never reaches os.replace."""
    path = tmp_path / "ckpt_10_0.ckpt"
    _write_ckpt(path, 10, 1000)
    with failpoints.active("ckpt.pre_fsync:raise:power-cut"):
        with pytest.raises(failpoints.FailpointError, match="power-cut"):
            save_state(str(path), {"iter_num": 99, "agent": np.full((3,), 99, np.float32)})
    assert load_state(str(path))["iter_num"] == 10


@pytest.mark.faults
def test_load_failpoint_corrupts_newest_once_and_spares_the_sibling(tmp_path):
    """`ckpt.load:corrupt:hit=1` bit-rots exactly the FIRST checkpoint the
    loader opens; the fallback re-entry (hit 2) must find its sibling intact."""
    _write_ckpt(tmp_path / "ckpt_10_0.ckpt", 10, 1000)
    newest = tmp_path / "ckpt_20_0.ckpt"
    _write_ckpt(newest, 20, 2000)
    with failpoints.active("ckpt.load:corrupt:hit=1"):
        with pytest.warns(UserWarning, match="older sibling"):
            state = load_state(str(newest))
    assert state["iter_num"] == 10


def test_gc_keep_last_prunes_oldest_and_never_counts_tmp(tmp_path):
    for i, mtime in [(1, 1000), (2, 2000), (3, 3000), (4, 4000)]:
        p = tmp_path / f"ckpt_{i}_0.ckpt"
        p.write_bytes(b"x")
        os.utime(p, (mtime, mtime))
    # an in-flight atomic write: must neither count toward keep_last nor be removed
    tmp = tmp_path / "ckpt_5_0.ckpt.tmp"
    tmp.write_bytes(b"partial")
    os.utime(tmp, (500, 500))  # even as the oldest file in the dir

    CheckpointCallback(keep_last=2)._gc(str(tmp_path))
    assert sorted(os.listdir(tmp_path)) == [
        "ckpt_3_0.ckpt",
        "ckpt_4_0.ckpt",
        "ckpt_5_0.ckpt.tmp",
    ]


def test_gc_disabled_keeps_everything(tmp_path):
    for i in range(3):
        (tmp_path / f"ckpt_{i}_0.ckpt").write_bytes(b"x")
    CheckpointCallback(keep_last=None)._gc(str(tmp_path))
    CheckpointCallback(keep_last=0)._gc(str(tmp_path))
    assert len(list(tmp_path.glob("*.ckpt"))) == 3
    # a vanished directory is a no-op, not a crash
    CheckpointCallback(keep_last=2)._gc(str(tmp_path / "missing"))


# --------------------------------------------------------------------------- #
# latest_certified edge cases (population-controller transfer medium)
# --------------------------------------------------------------------------- #


def _write_certified(path, iter_num, mtime):
    """A real checkpoint with a truthful last_good sidecar, pinned mtime."""
    facts = save_state(str(path), {"iter_num": iter_num, "agent": np.full((3,), iter_num, np.float32)})
    certify(str(path), crc32=facts["crc32"], size=facts["size"], policy_step=iter_num)
    os.utime(path, (mtime, mtime))
    return facts


def test_latest_certified_skips_sidecar_whose_checkpoint_was_deleted(tmp_path):
    _write_certified(tmp_path / "ckpt_16_0.ckpt", 16, 1000)
    newest = tmp_path / "ckpt_32_0.ckpt"
    _write_certified(newest, 32, 2000)
    os.remove(newest)  # sidecar survives, checkpoint is gone (e.g. manual cleanup)
    assert os.path.exists(str(newest) + ".certified.json")
    assert latest_certified(str(tmp_path)) == str(tmp_path / "ckpt_16_0.ckpt")
    # no certified checkpoint at all -> None, not a crash
    os.remove(tmp_path / "ckpt_16_0.ckpt")
    assert latest_certified(str(tmp_path)) is None
    assert latest_certified(str(tmp_path / "missing_dir")) is None


def test_latest_certified_skips_crc_mismatch_to_next_newest_sibling(tmp_path):
    """A same-size overwrite AFTER certification fools the size check alone;
    the sidecar-vs-footer CRC comparison must catch it and fall back to the
    next-newest certified sibling."""
    older = tmp_path / "ckpt_16_0.ckpt"
    _write_certified(older, 16, 1000)
    newest = tmp_path / "ckpt_32_0.ckpt"
    facts = _write_certified(newest, 32, 2000)
    # overwrite with different state of the SAME shapes -> same byte size,
    # different footer CRC; keep the sidecar and mtime as certification left them
    save_state(str(newest), {"iter_num": 99, "agent": np.full((3,), 99, np.float32)})
    os.utime(newest, (2000, 2000))
    assert os.path.getsize(newest) == facts["size"]
    assert read_footer_crc(str(newest)) != facts["crc32"]
    assert not is_certified(str(newest))
    assert latest_certified(str(tmp_path)) == str(older)


def test_latest_certified_breaks_mtime_ties_by_step_in_name(tmp_path):
    """Coarse-mtime filesystems (or a checkpoint burst within one second)
    produce ties; the numeric step embedded in the filename must break them
    toward the later training state, deterministically."""
    a = tmp_path / "ckpt_16_0.ckpt"
    b = tmp_path / "ckpt_32_0.ckpt"
    _write_certified(b, 32, 5000)  # written FIRST but carries the later step
    _write_certified(a, 16, 5000)
    assert os.path.getmtime(a) == os.path.getmtime(b)
    assert latest_certified(str(tmp_path)) == str(b)


def test_read_footer_crc_matches_save_state_and_rejects_legacy(tmp_path):
    import pickle

    path = tmp_path / "ckpt_8_0.ckpt"
    facts = save_state(str(path), {"iter_num": 8, "agent": np.zeros((4,), np.float32)})
    assert read_footer_crc(str(path)) == facts["crc32"]
    legacy = tmp_path / "legacy.ckpt"
    with open(legacy, "wb") as f:
        pickle.dump({"iter_num": 1}, f, protocol=pickle.HIGHEST_PROTOCOL)
    assert read_footer_crc(str(legacy)) is None  # bare pickle: no footer
    assert read_footer_crc(str(tmp_path / "missing.ckpt")) is None


def test_certified_under_walks_incarnation_subdirs(tmp_path):
    """The population controller keeps each trial incarnation in its own run
    dir; the exploit/explore transfer medium is the newest certified checkpoint
    across ALL of them."""
    gen0 = tmp_path / "inc0000" / "checkpoints"
    gen1 = tmp_path / "inc0003" / "checkpoints"
    gen0.mkdir(parents=True)
    gen1.mkdir(parents=True)
    _write_certified(gen0 / "ckpt_16_0.ckpt", 16, 1000)
    _write_certified(gen1 / "ckpt_48_0.ckpt", 48, 3000)
    uncert = gen1 / "ckpt_64_0.ckpt"
    save_state(str(uncert), {"iter_num": 64})  # newer but NEVER certified
    os.utime(uncert, (4000, 4000))
    assert certified_under(str(tmp_path)) == str(gen1 / "ckpt_48_0.ckpt")
    assert certified_under(str(tmp_path / "void")) is None
