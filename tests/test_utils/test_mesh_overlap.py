"""Overlap-scheduled mesh training pins (``-m mesh``).

Three contracts from ROADMAP item 2's handoff/overlap work:

1. **One put per device shard.** ``handoff.shard_put`` assembles a mesh-sharded
   batch with exactly one explicit ``device_put`` per device shard — byte
   accounting matches arithmetic, the whole assembly survives
   ``jax.transfer_guard("disallow")`` (no hidden implicit transfer anywhere),
   indivisible axes degrade per leaf, and re-putting an already-assembled tree
   is free.

2. **Microbatched gradients are bit-exact.** ``overlap.accumulate_grads``
   reproduces the single-batch ``value_and_grad`` (+ ``pmean`` under
   ``shard_map``) result bit-for-bit on integer-valued data with power-of-two
   chunking — the accumulation scan and per-bucket ``psum`` reorder collectives
   for the latency-hiding scheduler without changing a single bit of math.

3. **The HLO collective auditor sees mesh programs and gates on them.**
   AOT-compiling a ``psum`` program records op counts/bytes in the program
   ledger row and the ``Program/*/collective_bytes`` gauges; the
   ``programs diff`` CLI exits 1 on a de-async'd collective or grown
   collective bytes (the overlap regression it exists to catch).

Plus the chaos seams: the ``handoff.shard_put`` / ``train.grad_sync``
failpoints are registered and drillable (raise + benign fire).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core.runtime import Runtime
from sheeprl_tpu.parallel import handoff, overlap

pytestmark = pytest.mark.mesh

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(scope="module")
def mesh():
    return Runtime(accelerator="cpu", devices=8, strategy="auto", precision="32-true").mesh


# --------------------------------------------------------------------------- #
# 1. the handoff: one put per shard, exact byte accounting
# --------------------------------------------------------------------------- #


def test_shard_put_one_put_per_shard(mesh):
    payload = {
        "obs": np.ones((16, 64, 8), np.float32),  # 32768 B, sharded on axis 0
        "rew": np.ones((16, 64), np.float32),  # 4096 B, sharded on axis 0
        "coef": np.float32(0.5),  # scalar: the one leaf that still replicates
    }
    handoff.reset_stats()
    with jax.transfer_guard("disallow"):  # every put must be explicit
        placed = handoff.shard_put(payload, mesh, batch_axis=0)

    s = handoff.stats()
    assert s["calls"] == 1 and s["leaves"] == 3
    # 8 single-shard puts per sharded leaf + 8 replicated puts for the scalar
    assert s["puts"] == 24
    assert s["replicated_leaves"] == 1
    # sharded leaves cross the wire exactly once; the scalar crosses 8x
    assert s["put_bytes"] == 32768 + 4096 + 4 * 8
    # strictly fewer bytes than the old replicate-everything handoff
    assert s["put_bytes"] < handoff.replicated_put_bytes(payload, mesh)

    assert tuple(placed["obs"].sharding.spec)[0] == "data"
    shards = placed["obs"].addressable_shards
    assert len(shards) == 8 and shards[0].data.shape == (2, 64, 8)
    np.testing.assert_array_equal(np.asarray(placed["obs"]), payload["obs"])


def test_shard_put_indivisible_axis_fallback(mesh):
    handoff.reset_stats()
    placed = handoff.shard_put(
        {
            "other_axis": np.zeros((7, 16), np.float32),  # 7 % 8 != 0 -> axis 1
            "no_axis": np.zeros((7, 3), np.float32),  # nothing divides -> replicate
        },
        mesh,
        batch_axis=0,
    )
    assert tuple(placed["other_axis"].sharding.spec) == (None, "data")
    assert all(a is None for a in placed["no_axis"].sharding.spec)
    assert handoff.stats()["replicated_leaves"] == 1


def test_shard_put_passthrough_is_free(mesh):
    placed = handoff.shard_put({"x": np.zeros((16, 4), np.float32)}, mesh)
    handoff.reset_stats()
    again = handoff.shard_put(placed, mesh)
    s = handoff.stats()
    assert s["puts"] == 0 and s["put_bytes"] == 0
    assert again["x"] is placed["x"]


def test_shard_specs_mirror_shard_put_layout(mesh):
    tree = {"a": np.zeros((16, 64), np.float32), "b": np.zeros((7, 3), np.int32)}
    specs = handoff.shard_specs(tree, mesh, batch_axis=0)
    placed = handoff.shard_put(tree, mesh, batch_axis=0)

    def _check(spec, arr):
        assert spec.shape == arr.shape and spec.dtype == arr.dtype
        assert spec.sharding == arr.sharding  # or AOT warmup rejects the batch

    jax.tree_util.tree_map(_check, specs, placed)


# --------------------------------------------------------------------------- #
# 2. microbatched gradient bit-parity
# --------------------------------------------------------------------------- #


def _integer_problem(batch_size: int, seed: int = 0):
    """Integer-valued f32 data + power-of-two chunking => every sum/division in
    both the reference and the microbatched path is exact, so the parity
    assertion can be bitwise instead of allclose."""
    rng = np.random.default_rng(seed)
    params = {"w": rng.integers(-2, 3, size=(8,)).astype(np.float32)}
    batch = {
        "x": rng.integers(-3, 4, size=(batch_size, 8)).astype(np.float32),
        "y": rng.integers(-8, 9, size=(batch_size,)).astype(np.float32),
    }

    def loss_fn(p, b):
        pred = b["x"] @ p["w"]
        return jnp.mean((pred - b["y"]) ** 2), jnp.mean(pred)

    return params, batch, jax.value_and_grad(loss_fn, has_aux=True)


@pytest.mark.parametrize("m", [2, 4, 8])
def test_accumulate_grads_bitwise_parity_single_device(m):
    params, batch, grad_fn = _integer_problem(32)
    (ref_loss, ref_aux), ref_grads = jax.jit(grad_fn)(params, batch)

    def micro(p, b):
        return overlap.accumulate_grads(grad_fn, p, b, microbatches=m)

    (loss, aux), grads = jax.jit(micro)(params, batch)
    np.testing.assert_array_equal(np.asarray(loss), np.asarray(ref_loss))
    np.testing.assert_array_equal(np.asarray(aux), np.asarray(ref_aux))
    np.testing.assert_array_equal(np.asarray(grads["w"]), np.asarray(ref_grads["w"]))


@pytest.mark.parametrize("m", [2, 4])
def test_accumulate_grads_bitwise_parity_on_mesh(mesh, m):
    from sheeprl_tpu.data.device_buffer import _shard_map

    params, batch, grad_fn = _integer_problem(64, seed=1)

    def ref_step(p, b):
        (loss, _aux), grads = grad_fn(p, b)
        return jax.lax.pmean(loss, "data"), jax.lax.pmean(grads, "data")

    def micro_step(p, b):
        # per-bucket psum inside the scan; grads come back already axis-averaged
        (loss, _aux), grads = overlap.accumulate_grads(
            grad_fn, p, b, microbatches=m, axis_name="data", axis_size=8
        )
        return jax.lax.pmean(loss, "data"), grads

    in_specs, out_specs = (P(), P("data")), (P(), P())
    ref = jax.jit(_shard_map(ref_step, mesh=mesh, in_specs=in_specs, out_specs=out_specs))
    mic = jax.jit(_shard_map(micro_step, mesh=mesh, in_specs=in_specs, out_specs=out_specs))

    b = handoff.shard_put(batch, mesh, batch_axis=0)
    ref_loss, ref_grads = ref(params, b)
    loss, grads = mic(params, b)
    np.testing.assert_array_equal(np.asarray(loss), np.asarray(ref_loss))
    np.testing.assert_array_equal(np.asarray(grads["w"]), np.asarray(ref_grads["w"]))


def test_accumulate_grads_rejects_indivisible_chunking():
    params, batch, grad_fn = _integer_problem(32)
    with pytest.raises(ValueError, match="grad_microbatches"):
        jax.eval_shape(
            lambda p, b: overlap.accumulate_grads(grad_fn, p, b, microbatches=5), params, batch
        )


# --------------------------------------------------------------------------- #
# 3. the HLO collective auditor + diff gate
# --------------------------------------------------------------------------- #


def test_mesh_program_collective_capture(mesh):
    from sheeprl_tpu.core import compile as jax_compile
    from sheeprl_tpu.data.device_buffer import _shard_map
    from sheeprl_tpu.telemetry import programs as tel_programs

    fn = _shard_map(
        lambda x: jax.lax.pmean(x, "data"), mesh=mesh, in_specs=(P("data"),), out_specs=P()
    )
    gfn = jax_compile.guarded_jit(fn, name="test.mesh_collective")
    x = handoff.shard_put(np.arange(256, dtype=np.float32).reshape(64, 4), mesh)
    gfn.aot_compile(jax_compile.specs_of(x))

    row = next(r for r in tel_programs.snapshot() if r["name"] == "test.mesh_collective")
    coll = row.get("collective")
    assert coll, "mesh program row is missing the HLO collective audit"
    assert coll["op_count"] >= 1 and coll["bytes"] > 0
    assert coll["async_pairs"] + coll["sync_ops"] == coll["op_count"]

    gauges = tel_programs.gauges()
    assert gauges["Program/test.mesh_collective/collective_bytes"] == float(coll["bytes"])
    assert gauges["Program/test.mesh_collective/collective_ops"] == float(coll["op_count"])


def _collective_row(name, async_pairs, sync_ops, nbytes):
    return {
        "name": name,
        "fingerprint": "fp0",
        "collective": {
            "op_count": async_pairs + sync_ops,
            "async_pairs": async_pairs,
            "sync_ops": sync_ops,
            "bytes": float(nbytes),
            "exposed_bytes": 0.0,
        },
    }


def test_programs_diff_cli_gates_overlap_regressions(tmp_path):
    """Doctored candidate ledger: the same program's all-reduce compiled as a
    plain sync op (de-async'd) and moved +20% bytes — both must be flagged and
    the CLI must exit 1 (the CI gate); a self-diff stays rc 0."""
    ledger_a = tmp_path / "a.jsonl"
    ledger_b = tmp_path / "b.jsonl"
    ledger_a.write_text(json.dumps(_collective_row("ppo.train", 2, 0, 1_000_000)) + "\n")
    ledger_b.write_text(json.dumps(_collective_row("ppo.train", 0, 2, 1_200_000)) + "\n")

    def _diff(a, b):
        return subprocess.run(
            [sys.executable, "-m", "sheeprl_tpu.telemetry.programs", "diff", "--json", a, b],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=120,
        )

    out = _diff(str(ledger_a), str(ledger_b))
    assert out.returncode == 1, out.stdout + out.stderr
    report = json.loads(out.stdout)
    assert any("de-async'd" in r for r in report["regressions"])
    assert any("collective bytes" in r for r in report["regressions"])
    (delta,) = report["collective_deltas"]
    assert delta["deasync"] and delta["regression"]

    clean = _diff(str(ledger_a), str(ledger_a))
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert json.loads(clean.stdout)["regressions"] == []


# --------------------------------------------------------------------------- #
# chaos seams
# --------------------------------------------------------------------------- #


@pytest.mark.faults
def test_handoff_and_grad_sync_failpoints_drill(mesh):
    for name in ("handoff.shard_put", "train.grad_sync"):
        assert failpoints.known()[name]["plane"] == "train"

    payload = {"x": np.zeros((16, 4), np.float32)}
    with failpoints.active("handoff.shard_put:raise"):
        with pytest.raises(failpoints.FailpointError):
            handoff.shard_put(payload, mesh)

    with failpoints.active("handoff.shard_put:fire,train.grad_sync:fire"):
        handoff.shard_put(payload, mesh)
        failpoints.failpoint("train.grad_sync", iter=0, microbatches=2)
        counts = failpoints.counts()
        assert counts["handoff.shard_put"]["fires"] == 1
        assert counts["train.grad_sync"]["fires"] == 1
