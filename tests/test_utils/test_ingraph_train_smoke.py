"""Satellite registration of scripts/ingraph_train_smoke.py as a tier-1 test:
fresh-interpreter fused whole-iteration PPO training (single-device AND the
2-device shard_map variant) must finish with zero retraces and leave a
finite-return-playing env behind — the cheapest end-to-end proof that the
fused train path stays wired through the config, compile, and algo layers."""

import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.ingraph
@pytest.mark.timeout(600)
def test_ingraph_train_smoke(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "ingraph_train_smoke.py"),
            "--workdir",
            str(tmp_path),
            "--timeout",
            "420",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-1500:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "ingraph train smoke OK" in out.stdout
