"""Satellite registration of scripts/chaos_smoke.py as a tier-1 test: a real
SIGTERM delivered to `bench.py --smoke` mid-iteration must yield a clean exit,
an emergency checkpoint, and a successful resume (full harness, fresh
interpreters, real signal delivery — the one test that is not in-process)."""

import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.faults
@pytest.mark.timeout(600)
def test_chaos_smoke_sigterm_roundtrip(tmp_path):
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "chaos_smoke.py"),
            "--workdir",
            str(tmp_path),
            "--timeout",
            "480",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert out.returncode == 0, f"stdout:\n{out.stdout[-1500:]}\nstderr:\n{out.stderr[-3000:]}"
    assert "chaos smoke OK" in out.stdout
    # the harness's own assertions already ran; re-check the artifact exists
    assert any(
        f.endswith(".ckpt") for _, _, fs in os.walk(tmp_path / "logs") for f in fs
    ), "no emergency checkpoint left on disk"
