import pytest

from sheeprl_tpu.utils.utils import Ratio


def test_ratio_one_to_one():
    r = Ratio(ratio=1.0)
    total = 0
    for step in range(1, 11):
        total += r(step * 4)
    assert total == 40


def test_ratio_fractional():
    r = Ratio(ratio=0.5)
    total = 0
    for step in range(1, 101):
        total += r(step)
    assert total == pytest.approx(50, abs=1)


def test_ratio_zero():
    r = Ratio(ratio=0.0)
    assert r(100) == 0


def test_ratio_pretrain():
    r = Ratio(ratio=1.0, pretrain_steps=16)
    assert r(20) == 16  # first call: int(pretrain_steps * ratio)
    assert r(24) == 4  # afterwards: delta from the first-call step count


def test_ratio_pretrain_scaled_by_ratio():
    r = Ratio(ratio=0.5, pretrain_steps=100)
    assert r(200) == 50


def test_ratio_pretrain_clamped_warns():
    import pytest as _pytest

    r = Ratio(ratio=1.0, pretrain_steps=16)
    with _pytest.warns(UserWarning):
        assert r(8) == 8  # pretrain clamped to current steps


def test_ratio_state_roundtrip():
    r = Ratio(ratio=0.25)
    r(10)
    state = r.state_dict()
    r2 = Ratio(ratio=1.0)
    r2.load_state_dict(state)
    assert r2.state_dict() == state


def test_ratio_invalid():
    with pytest.raises(ValueError):
        Ratio(ratio=-1)
    with pytest.raises(ValueError):
        Ratio(ratio=1, pretrain_steps=-1)
