"""Env wrapper unit tests (reference tests/test_envs: dilated FrameStack,
actions-as-obs, RestartOnException)."""

import numpy as np
import gymnasium as gym
import pytest

from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    ActionsAsObservationWrapper,
    FallbackRecordVideo,
    FrameStack,
    RestartOnException,
    RewardAsObservationWrapper,
)


class _CountingEnv(gym.Env):
    """Dict obs {rgb, step}: rgb filled with the step counter."""

    def __init__(self, episode_len: int = 100):
        self.observation_space = gym.spaces.Dict(
            {
                "rgb": gym.spaces.Box(0, 255, (3, 4, 4), np.uint8),
                "state": gym.spaces.Box(-np.inf, np.inf, (2,), np.float32),
            }
        )
        self.action_space = gym.spaces.Discrete(3)
        self._t = 0
        self._episode_len = episode_len
        self.reward_range = (0.0, 1.0)

    def _obs(self):
        return {
            "rgb": np.full((3, 4, 4), self._t % 256, dtype=np.uint8),
            "state": np.array([self._t, 0], dtype=np.float32),
        }

    def step(self, action):
        self._t += 1
        return self._obs(), 1.0, self._t >= self._episode_len, False, {}

    def reset(self, *, seed=None, options=None):
        self._t = 0
        return self._obs(), {}


def test_action_repeat_sums_rewards():
    env = ActionRepeat(_CountingEnv(), amount=4)
    env.reset()
    _, reward, _, _, _ = env.step(0)
    assert reward == 4.0
    assert env.unwrapped._t == 4


def test_action_repeat_stops_at_done():
    env = ActionRepeat(_CountingEnv(episode_len=2), amount=5)
    env.reset()
    _, reward, terminated, _, _ = env.step(0)
    assert reward == 2.0 and terminated


def test_frame_stack_shapes_and_reset_fill():
    env = FrameStack(_CountingEnv(), num_stack=3, cnn_keys=["rgb"])
    obs, _ = env.reset()
    assert obs["rgb"].shape == (3, 3, 4, 4)
    # reset fills the deque with copies of the first frame
    assert (obs["rgb"] == obs["rgb"][0]).all()
    obs, *_ = env.step(0)
    assert obs["rgb"][-1].max() == 1  # newest frame is step 1


def test_frame_stack_dilation_picks_every_dth():
    env = FrameStack(_CountingEnv(), num_stack=2, cnn_keys=["rgb"], dilation=2)
    env.reset()
    for _ in range(4):
        obs, *_ = env.step(0)
    # window holds frames [1,2,3,4]; dilation 2 picks [2, 4]
    assert obs["rgb"][0].max() == 2 and obs["rgb"][1].max() == 4


def test_frame_stack_rejects_zero_stack():
    with pytest.raises(ValueError):
        FrameStack(_CountingEnv(), num_stack=0, cnn_keys=["rgb"])


class _FlakyEnv(_CountingEnv):
    """Raises once on the first step after construction."""

    crashes = 0

    def step(self, action):
        if type(self).crashes < 1:
            type(self).crashes += 1
            raise RuntimeError("boom")
        return super().step(action)


def test_restart_on_exception_rebuilds_and_flags():
    _FlakyEnv.crashes = 0
    env = RestartOnException(lambda: _FlakyEnv(), wait=0.0)
    env.reset()
    obs, reward, terminated, truncated, info = env.step(0)
    assert info.get("restart_on_exception") is True
    assert reward == 0.0 and not terminated and not truncated
    # the rebuilt env works normally afterwards
    _, reward, _, _, info = env.step(0)
    assert reward == 1.0 and "restart_on_exception" not in info


def test_restart_on_exception_gives_up_after_maxfails():
    class AlwaysBroken(_CountingEnv):
        def step(self, action):
            raise RuntimeError("always")

    env = RestartOnException(lambda: AlwaysBroken(), maxfails=2, wait=0.0)
    env.reset()
    env.step(0)
    env.step(0)
    with pytest.raises(RuntimeError, match="crashed too many times"):
        env.step(0)


def test_reward_as_observation():
    env = RewardAsObservationWrapper(_CountingEnv())
    obs, _ = env.reset()
    assert obs["reward"] == np.float32(0.0)
    obs, *_ = env.step(0)
    assert obs["reward"] == np.float32(1.0)
    assert "reward" in env.observation_space.spaces


def test_actions_as_observation_discrete_one_hot():
    env = ActionsAsObservationWrapper(_CountingEnv(), num_stack=2, noop=0)
    obs, _ = env.reset()
    assert obs["action_stack"].shape == (6,)  # 2 stacked one-hots of dim 3
    np.testing.assert_allclose(obs["action_stack"], [1, 0, 0, 1, 0, 0])
    obs, *_ = env.step(2)
    np.testing.assert_allclose(obs["action_stack"], [1, 0, 0, 0, 0, 1])


def test_actions_as_observation_rejects_bad_noop():
    with pytest.raises(ValueError):
        ActionsAsObservationWrapper(_CountingEnv(), num_stack=2, noop=[0, 1])


class _RenderingEnv(_CountingEnv):
    render_mode = "rgb_array"

    def render(self):
        return np.full((8, 8, 3), self._t % 256, dtype=np.uint8)


def test_fallback_record_video_writes_gifs(tmp_path):
    env = FallbackRecordVideo(_RenderingEnv(episode_len=3), str(tmp_path / "vids"), fps=10)
    env.reset()
    for ep in range(2):
        done = False
        while not done:
            _, _, terminated, truncated, _ = env.step(0)
            done = terminated or truncated
        if ep == 0:
            env.reset()
    env.close()
    gifs = sorted(p.name for p in (tmp_path / "vids").glob("*.gif"))
    assert gifs == ["episode_0.gif", "episode_1.gif"]
    assert (tmp_path / "vids" / "episode_0.gif").stat().st_size > 0


def test_fallback_record_video_partial_episode_keeps_index(tmp_path):
    """An early reset flushes the partial recording WITHOUT overwriting it later."""
    env = FallbackRecordVideo(_RenderingEnv(episode_len=10), str(tmp_path / "vids"), fps=10)
    env.reset()
    env.step(0)
    env.reset()  # mid-episode: partial episode_0.gif, index advances
    done = False
    while not done:
        _, _, terminated, truncated, _ = env.step(0)
        done = terminated or truncated
    env.close()
    gifs = sorted(p.name for p in (tmp_path / "vids").glob("*.gif"))
    assert gifs == ["episode_0.gif", "episode_1.gif"]


def test_fallback_record_video_trigger_and_frame_cap(tmp_path):
    env = FallbackRecordVideo(
        _RenderingEnv(episode_len=6),
        str(tmp_path / "vids"),
        fps=10,
        episode_trigger=lambda ep: ep == 1,
        max_frames=3,
    )
    for _ in range(2):
        env.reset()
        done = False
        while not done:
            _, _, terminated, truncated, _ = env.step(0)
            done = terminated or truncated
    env.close()
    gifs = sorted(p.name for p in (tmp_path / "vids").glob("*.gif"))
    assert gifs == ["episode_1.gif"]  # episode 0 skipped by the trigger
    assert len(env._frames) == 0
