"""Whole-iteration fused in-graph training (envs/ingraph/fused.py).

Pins the tentpole guarantees:
- fused-vs-split BIT-parity: the fused iteration inlines the collector's
  ``collect_impl`` and the algo's ``make_update_impl`` output — the same
  expressions the split path jits separately — so params, trajectories, and
  losses must agree bit-for-bit, per iteration, on CartPole and GridWorld;
- a warm fused iteration performs metrics-only host traffic (the whole
  rollout + GAE + update epochs run under ``jax.transfer_guard("disallow")``);
- the ``shard_map`` variant trains on a 2-device mesh without retracing;
- the ``train.fused_update`` chaos seam fires on the fused path;
- the SAC replay-ring wiring trains end-to-end through the real CLI.

Every split/fused pair in one process needs SEPARATE collector (and env)
instances: ``lax.scan`` caches the body jaxpr keyed on the body function
object, so tracing both paths over one collector's shared ``one_step``
closure replays the first trace's captured param tracers into the second
(UnexpectedTracerError). Production processes only ever trace one path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.ppo import make_train_fn, make_update_impl
from sheeprl_tpu.config import instantiate, load_config
from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core.runtime import build_runtime
from sheeprl_tpu.envs import ingraph as ig
from sheeprl_tpu.utils.optim import with_clipping
from sheeprl_tpu.utils.utils import PlayerParamsSync

pytestmark = pytest.mark.ingraph

N_ENVS = 16
T = 8
N_DATA = N_ENVS * T


def _load_cfg(env_name: str, extra=()):
    return load_config(
        overrides=[
            "exp=ppo",
            f"env={env_name}",
            f"env.num_envs={N_ENVS}",
            f"algo.rollout_steps={T}",
            f"algo.per_rank_batch_size={N_DATA // 2}",
            "algo.update_epochs=2",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "seed=7",
            *extra,
        ]
    )


def _build_stack(cfg, runtime, name: str):
    """One independent (venv, agent, optimizer, collector) world; building it
    twice from the same cfg reproduces identical init bits on both sides."""
    import gymnasium as gym

    venv = ig.make_vector_env(cfg, N_ENVS, cfg.seed, device=runtime.device)
    space = venv.single_action_space
    is_continuous = isinstance(space, gym.spaces.Box)
    actions_dim = (
        tuple(space.shape) if is_continuous else (int(space.n),)
    )
    agent, params, player = build_agent(
        runtime, actions_dim, is_continuous, cfg, venv.single_observation_space, None
    )
    player.params = jax.device_put(player.params, runtime.device)
    venv.reset(seed=cfg.seed)
    collector = ig.InGraphRolloutCollector(
        venv, player, rollout_steps=T, gamma=float(cfg.algo.gamma), name=name
    )
    tx = with_clipping(instantiate(dict(cfg.algo.optimizer))(), cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    params_sync = PlayerParamsSync(player.params)
    return venv, agent, params, player, collector, tx, opt_state, params_sync


def _extras(cfg):
    return (
        jnp.float32(cfg.algo.clip_coef),
        jnp.float32(cfg.algo.ent_coef),
        jnp.float32(1.0),
    )


@pytest.mark.timeout(300)
@pytest.mark.parametrize("env_name", ["jax_cartpole", "jax_gridworld"])
def test_fused_matches_split_bitwise(env_name):
    cfg = _load_cfg(env_name)
    runtime = build_runtime(cfg.fabric)
    extras = _extras(cfg)

    # ----- split reference: jitted collect, then the jitted train step
    venv_s, agent_s, params_s, player_s, collector_s, tx_s, opt_s, sync_s = _build_stack(
        cfg, runtime, "split"
    )
    train_fn = make_train_fn(agent_s, tx_s, cfg, runtime, N_DATA, ["state"], [], sync_s)
    split_rolls, split_trains = [], []
    for i in range(2):
        player_s.params = params_s  # the loop's params_sync refresh, bit-exact
        data, roll_metrics, next_values = collector_s.collect()
        key = jax.random.fold_in(jax.random.PRNGKey(99), i)
        params_s, opt_s, _flat, train_metrics = train_fn(
            params_s, opt_s, data, next_values, key, *extras
        )
        split_rolls.append(jax.tree_util.tree_map(np.asarray, roll_metrics))
        split_trains.append({k: np.asarray(v) for k, v in train_metrics.items()})

    # ----- fused path on a fresh identical world (same seeds => same bits)
    venv_f, agent_f, params_f, _player_f, collector_f, tx_f, opt_f, sync_f = _build_stack(
        cfg, runtime, "fused"
    )
    update_impl = make_update_impl(agent_f, tx_f, cfg, runtime, N_DATA, ["state"], [], sync_f)
    trainer = ig.FusedInGraphTrainer(collector_f, update_impl, n_extras=3, name="paritytest")
    for i in range(2):
        key = jax.random.fold_in(jax.random.PRNGKey(99), i)
        params_f, opt_f, _flat, roll_metrics, train_metrics = trainer.step(
            params_f, opt_f, key, *extras
        )
        fused_roll = jax.tree_util.tree_map(np.asarray, roll_metrics)
        for k, v in split_rolls[i].items():
            np.testing.assert_array_equal(fused_roll[k], v, err_msg=f"iter {i} roll {k}")
        for k, v in split_trains[i].items():
            np.testing.assert_array_equal(
                np.asarray(train_metrics[k]), v, err_msg=f"iter {i} train {k}"
            )

    # post-update params AND the env carry chain are bit-identical
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params_s,
        params_f,
    )
    np.testing.assert_array_equal(np.asarray(venv_s.carry.obs), np.asarray(venv_f.carry.obs))
    venv_s.close()
    venv_f.close()


@pytest.mark.timeout(300)
def test_fused_iteration_makes_zero_host_transfers():
    """A warm fused iteration — rollout scan + GAE + every update epoch — runs
    under ``jax.transfer_guard("disallow")``: no per-phase host pulls, no
    implicit uploads; the episode/loss metric pulls happen on demand AFTER the
    guard lifts. The guard is proven live by the explicit upload raising."""
    cfg = _load_cfg("jax_cartpole")
    runtime = build_runtime(cfg.fabric)
    venv, agent, params, _player, collector, tx, opt_state, sync = _build_stack(
        cfg, runtime, "zt_fused"
    )
    update_impl = make_update_impl(agent, tx, cfg, runtime, N_DATA, ["state"], [], sync)
    trainer = ig.FusedInGraphTrainer(collector, update_impl, n_extras=3, name="zt_fused")
    extras = _extras(cfg)
    # index the key batch OUTSIDE the guard (x[i] uploads the host index)
    k0, k1, k2 = (k for k in jax.random.split(jax.random.PRNGKey(5), 3))

    params, opt_state, flat, _r, _t = trainer.step(params, opt_state, k0, *extras)
    jax.block_until_ready(flat)

    with jax.transfer_guard("disallow"):
        params, opt_state, flat, roll_metrics, train_metrics = trainer.step(
            params, opt_state, k1, *extras
        )
        # carry chains device-to-device across iterations
        params, opt_state, flat, roll_metrics, train_metrics = trainer.step(
            params, opt_state, k2, *extras
        )
        jax.block_until_ready(flat)  # fence only — not a transfer
        with pytest.raises(Exception):
            jnp.add(flat, 1.0)  # implicit host->device upload: guard is live

    assert np.isfinite(np.asarray(train_metrics["Loss/policy_loss"]))
    assert np.asarray(roll_metrics["dones"]).shape == (T, N_ENVS)
    assert np.asarray(flat).ndim == 1  # the one-transfer player refresh vector
    venv.close()


@pytest.mark.timeout(300)
def test_fused_sharded_two_device_mesh():
    """The shard_map variant: env batch on the ``data`` axis, pmean'd grads,
    replicated params — two steady-state steps, zero retraces, [T, B] episode
    metrics reassembled across shards."""
    if len(jax.local_devices()) < 2:
        pytest.skip("needs >= 2 local devices (conftest forces 8 on CPU)")
    cfg = _load_cfg("jax_cartpole", extra=["fabric.devices=2"])
    runtime = build_runtime(cfg.fabric)
    assert runtime.world_size == 2
    venv, agent, params, _player, collector, tx, opt_state, sync = _build_stack(
        cfg, runtime, "sharded"
    )
    update_impl = make_update_impl(
        agent, tx, cfg, runtime, N_DATA, ["state"], [], sync, axis_name="data", shards=2
    )
    trainer = ig.FusedInGraphTrainer(
        collector, update_impl, n_extras=3, mesh=runtime.mesh, name="shardedtest"
    )
    trainer.shard_carry()
    extras = tuple(trainer.to_mesh(e) for e in _extras(cfg))
    key = trainer.to_mesh(jax.random.PRNGKey(11))
    for i in range(3):
        key_i = trainer.to_mesh(jax.random.fold_in(key, i))
        params, opt_state, flat, roll_metrics, train_metrics = trainer.step(
            params, opt_state, key_i, *extras
        )
    assert trainer.step_fn.retraces == 0, "sharded fused step retraced"
    assert np.asarray(roll_metrics["dones"]).shape == (T, N_ENVS)
    assert np.isfinite(np.asarray(train_metrics["Loss/value_loss"]))
    assert all(np.all(np.isfinite(np.asarray(x))) for x in jax.tree_util.tree_leaves(params))
    venv.close()


@pytest.mark.faults
def test_fused_update_failpoint_covers_fused_path(standard_args, tmp_path, monkeypatch):
    """The ``train.fused_update`` chaos seam fires once per fused iteration,
    BEFORE the compiled step — a raise surfaces out of the real CLI run."""
    monkeypatch.chdir(tmp_path)
    from sheeprl_tpu.cli import run

    args = standard_args + [
        "exp=ppo",
        "env=jax_cartpole",
        "env.num_envs=4",
        "algo.rollout_steps=2",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "buffer.memmap=False",
    ]
    with failpoints.active("train.fused_update:raise:chaos-fused"):
        with pytest.raises(failpoints.FailpointError, match="chaos-fused"):
            run(overrides=args)


@pytest.mark.timeout(480)
def test_sac_ingraph_replay_ring_end_to_end(standard_args, tmp_path, monkeypatch):
    """SAC on the ingraph backend: uniform-action prefill into the HBM replay
    ring, then fused collect+update iterations sampling the ring in-graph —
    through the real CLI (exp=sac pins a LunarLander id, so env.id is
    re-pointed at the in-graph Pendulum port)."""
    monkeypatch.chdir(tmp_path)
    from sheeprl_tpu.cli import run

    args = standard_args + [
        "exp=sac",
        "env=jax_pendulum",
        "env.id=Pendulum-v1",
        "env.num_envs=4",
        "dry_run=False",
        "algo.total_steps=96",
        "algo.ingraph_collect_steps=4",
        "algo.learning_starts=32",
        "algo.per_rank_batch_size=16",
        "algo.hidden_size=8",
        "algo.run_test=False",
        "buffer.size=512",
        "buffer.memmap=False",
        "metric.disable_timer=True",
    ]
    run(overrides=args)
