"""Trajectory parity and zero-transfer guarantees for the in-graph env backend.

Parity contract (howto/ingraph_envs.md): with ``dtype=float64`` the eager
per-op dynamics are BIT-equal to the Gymnasium reference (same expression
order, same operand dtypes); under ``jit``/``scan`` XLA's FMA contraction can
drift the f64 state by 1-2 ULP per step, which the f32 observation cast
absorbs — so the scanned tests assert exact f32 obs/reward/done parity while
the eager tests assert raw f64 state bit-parity. Episode boundaries are
covered by injecting our reset state into the Gymnasium env and continuing.
"""

from __future__ import annotations

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import enable_x64

from sheeprl_tpu.envs.ingraph import CartPole, GridWorld, Pendulum, autoreset_step

pytestmark = pytest.mark.ingraph


def _sync_gym_to(gym_env, y) -> None:
    """Reset the Gymnasium env's bookkeeping and inject our state into it."""
    gym_env.reset()
    gym_env.unwrapped.state = np.asarray(y, dtype=np.float64)


def test_cartpole_eager_f64_bit_parity_with_resets():
    """>=200 steps of eager f64 CartPole match Gymnasium BIT-for-bit, with the
    episode boundaries crossed by re-seeding both sides from our reset."""
    with enable_x64():
        env = CartPole()
        params = env.default_params(dtype=jnp.float64)
        gym_env = gym.make("CartPole-v1", disable_env_checker=True)
        key = jax.random.PRNGKey(0)
        key, k0 = jax.random.split(key)
        state, _ = env.reset(k0, params)
        _sync_gym_to(gym_env, state.y)

        rng = np.random.default_rng(7)
        episodes = 0
        for _ in range(250):
            a = int(rng.integers(0, 2))
            key, ks = jax.random.split(key)
            state, obs, reward, done, info = env.step(ks, state, jnp.int32(a), params)
            g_obs, g_reward, g_term, _g_trunc, _ = gym_env.step(a)
            np.testing.assert_array_equal(
                np.asarray(state.y), np.asarray(gym_env.unwrapped.state, dtype=np.float64)
            )
            np.testing.assert_array_equal(np.asarray(obs), g_obs)
            assert float(reward) == float(g_reward) == 1.0
            assert bool(info["terminated"]) == bool(g_term)
            if bool(done):
                episodes += 1
                key, kr = jax.random.split(key)
                state, _ = env.reset(kr, params)
                _sync_gym_to(gym_env, state.y)
        assert episodes >= 2, "random policy should end several episodes in 250 steps"
        gym_env.close()


def test_cartpole_scanned_autoreset_parity():
    """The fused scan path (autoreset_step under jit+lax.scan) reproduces the
    Gymnasium transition at every step — f32 obs/reward/done — including the
    auto-reset boundaries, where the pre-reset obs rides in terminal_obs and
    the emitted obs is already the next episode's start."""
    T = 300
    with enable_x64():
        env = CartPole()
        params = env.default_params(dtype=jnp.float64)
        step = autoreset_step(env, params)
        key = jax.random.PRNGKey(3)
        key, k0 = jax.random.split(key)
        init_state, _ = env.reset(k0, params)
        rng = np.random.default_rng(11)
        actions = jnp.asarray(rng.integers(0, 2, size=(T,)), dtype=jnp.int32)
        keys = jax.random.split(key, T)

        def body(state, xs):
            k, a = xs
            state, obs, reward, done, info = step(k, state, a)
            return state, (obs, reward, done, info["terminal_obs"], state.y)

        _, (obs_seq, rew_seq, done_seq, term_obs_seq, y_seq) = jax.jit(
            lambda s: jax.lax.scan(body, s, (keys, actions))
        )(init_state)
        obs_seq, rew_seq, done_seq, term_obs_seq, y_seq = jax.tree_util.tree_map(
            np.asarray, (obs_seq, rew_seq, done_seq, term_obs_seq, y_seq)
        )

        gym_env = gym.make("CartPole-v1", disable_env_checker=True)
        _sync_gym_to(gym_env, init_state.y)
        boundaries = 0
        for t in range(T):
            g_obs, g_reward, g_term, g_trunc, _ = gym_env.step(int(actions[t]))
            # the pre-reset obs always tracks the reference transition
            np.testing.assert_array_equal(term_obs_seq[t], g_obs)
            assert float(rew_seq[t]) == float(g_reward)
            assert bool(done_seq[t]) == bool(g_term or g_trunc)
            if bool(done_seq[t]):
                boundaries += 1
                # auto-reset: the emitted obs is a fresh episode, not the terminal one
                assert not np.array_equal(obs_seq[t], term_obs_seq[t])
            # resync gym (and its TimeLimit) to the scan's post-step state so each
            # step is an independent one-step reference, reset branches included
            _sync_gym_to(gym_env, y_seq[t])
        assert boundaries >= 2
        gym_env.close()


def test_pendulum_eager_f64_parity_and_truncation():
    """200 steps of eager f64 Pendulum match Gymnasium bit-for-bit (state),
    exactly (f32 obs/reward), and both sides truncate at step 200."""
    with enable_x64():
        env = Pendulum()
        params = env.default_params(dtype=jnp.float64)
        gym_env = gym.make("Pendulum-v1", disable_env_checker=True)
        key = jax.random.PRNGKey(1)
        key, k0 = jax.random.split(key)
        state, _ = env.reset(k0, params)
        _sync_gym_to(gym_env, state.y)

        rng = np.random.default_rng(5)
        for t in range(200):
            a = rng.uniform(-2.0, 2.0, size=(1,))
            key, ks = jax.random.split(key)
            state, obs, reward, done, info = env.step(ks, state, jnp.asarray(a), params)
            g_obs, g_reward, g_term, g_trunc, _ = gym_env.step(a)
            np.testing.assert_array_equal(
                np.asarray(state.y), np.asarray(gym_env.unwrapped.state, dtype=np.float64)
            )
            np.testing.assert_array_equal(np.asarray(obs), g_obs)
            assert np.float32(g_reward) == np.asarray(reward)
            assert not bool(info["terminated"]) and not bool(g_term)
            if t < 199:
                assert not bool(done) and not bool(g_trunc)
        assert bool(done) and bool(info["truncated"]) and bool(g_trunc)
        gym_env.close()


def test_gridworld_procedural_layouts():
    """Same key => same scenario; distinct keys => distinct scenarios; every
    layout keeps start/goal distinct and off the obstacles."""
    env = GridWorld()
    params = env.default_params()
    _, o_a = env.reset(jax.random.PRNGKey(0), params)
    _, o_b = env.reset(jax.random.PRNGKey(0), params)
    np.testing.assert_array_equal(np.asarray(o_a), np.asarray(o_b))

    layouts = set()
    for i in range(8):
        st, obs = env.reset(jax.random.PRNGKey(i), params)
        obstacles = np.asarray(st.obstacles)
        assert not obstacles[tuple(np.asarray(st.pos))]
        assert not obstacles[tuple(np.asarray(st.goal))]
        assert not np.array_equal(np.asarray(st.pos), np.asarray(st.goal))
        assert int(obstacles.sum()) == params.n_obstacles
        o = np.asarray(obs)
        assert o.shape == (3 * params.size**2,) and o.min() >= 0.0 and o.max() <= 1.0
        layouts.add(obstacles.tobytes() + np.asarray(st.pos).tobytes())
    assert len(layouts) >= 7, "procedural family should vary across keys"


def test_gridworld_truncation_and_fresh_layout_on_reset():
    """The in-graph TimeLimit ends a goal-less crawl at max_episode_steps and
    the auto-reset hands back a (typically different) fresh scenario."""
    env = GridWorld()
    params = env.default_params(max_episode_steps=4)
    step = autoreset_step(env, params)
    key = jax.random.PRNGKey(2)
    key, k0 = jax.random.split(key)
    state, _ = env.reset(k0, params)
    first_goal = np.asarray(state.goal)
    dones = []
    for t in range(4):
        key, ks = jax.random.split(key)
        # walking into the top wall never reaches the goal => pure TimeLimit test
        state, obs, reward, done, info = step(ks, state, jnp.int32(0))
        dones.append(bool(done))
        if not done:
            assert float(reward) == pytest.approx(params.step_penalty)
    assert dones == [False, False, False, True]
    assert int(state.t) == 0, "auto-reset must restart the episode clock"
    # the reset drew a fresh scenario from the key chain (deterministic given seed)
    assert not np.array_equal(np.asarray(state.goal), first_goal)


@pytest.mark.timeout(300)
def test_fused_collect_makes_zero_host_transfers():
    """A warm fused rollout runs to completion under ``jax.transfer_guard``:
    no per-step host pulls, no implicit uploads — the ISSUE's zero-transfer
    guarantee, pinned. The guard is proven live by the explicit pull raising."""
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.config import load_config
    from sheeprl_tpu.core.runtime import build_runtime
    from sheeprl_tpu.envs import ingraph as ig

    cfg = load_config(
        overrides=[
            "exp=ppo",
            "env=jax_cartpole",
            "env.num_envs=16",
            "algo.rollout_steps=8",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
        ]
    )
    runtime = build_runtime(cfg.fabric)
    venv = ig.make_vector_env(cfg, 16, 0, device=runtime.device)
    _, _, player = build_agent(runtime, (2,), False, cfg, venv.single_observation_space, None)
    player.params = jax.device_put(player.params, runtime.device)
    venv.reset(seed=0)
    collector = ig.InGraphRolloutCollector(venv, player, rollout_steps=8, gamma=0.99, name="zt")
    collector.collect()  # compile outside the guard
    jax.block_until_ready(venv.carry.obs)

    with jax.transfer_guard("disallow"):
        data, metrics, next_values = collector.collect()
        collector.collect()  # carry chains stay on device across iterations
        jax.block_until_ready(venv.carry.obs)  # fence only — not a transfer
        # sanity that the guard is live: an implicit host->device upload (the
        # python scalar) must raise, so a silent pass above is meaningful
        with pytest.raises(Exception):
            jnp.add(data["rewards"], 1.0)

    rewards = np.asarray(data["rewards"])
    assert rewards.shape == (8, 16, 1)
    assert np.asarray(data[venv.obs_key]).shape == (8, 16, 4)
    assert np.asarray(next_values).shape == (16, 1)
    # CartPole pays 1.0 per step, so every finished episode has return == length
    from sheeprl_tpu.envs.ingraph import iter_finished_episodes

    for ep_ret, ep_len in iter_finished_episodes(metrics):
        assert ep_ret == pytest.approx(float(ep_len))
