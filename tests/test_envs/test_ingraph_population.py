"""Device-resident vmapped population training (envs/ingraph/population.py).

Pins the tentpole guarantees:
- a population of ONE is bitwise-identical to the single-member
  ``FusedInGraphTrainer`` — params AND optimizer state after K iterations
  (the static pop-of-1 branch runs the unbatched member trace, so the f32
  reduction order matches exactly);
- the in-graph ``exploit_plan`` reproduces the host PBT helpers' math
  (``resow.bottom_quantile`` selection with stable tie-breaking,
  ``resow.perturb`` multiplicative factor choice);
- AOT warmup from ``stacked_specs`` (single-member live values, BEFORE the
  population is materialized) leaves zero retraces across epochs + exploits;
- the ``shard_map`` variant trains an 8-member population on a forced
  8-device CPU mesh (member axis on ``data``) without retracing;
- domain randomization samples valid per-member physics and actually changes
  the dynamics each member trains under;
- the ``population.exploit`` / ``population.member_sync`` chaos seams are
  registered and fire.

Same caveat as the fused tests: every traced path needs its OWN collector
instance (``lax.scan`` caches the body jaxpr on the body function object).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.ppo import make_update_impl
from sheeprl_tpu.config import instantiate, load_config
from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core.runtime import build_runtime
from sheeprl_tpu.envs import ingraph as ig
from sheeprl_tpu.orchestrate import resow
from sheeprl_tpu.utils.optim import with_clipping
from sheeprl_tpu.utils.utils import PlayerParamsSync

pytestmark = pytest.mark.ingraph

N_ENVS = 16
T = 8
N_DATA = N_ENVS * T


def _load_cfg(env_name: str, extra=()):
    return load_config(
        overrides=[
            "exp=ppo",
            f"env={env_name}",
            f"env.num_envs={N_ENVS}",
            f"algo.rollout_steps={T}",
            f"algo.per_rank_batch_size={N_DATA // 2}",
            "algo.update_epochs=2",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "seed=7",
            *extra,
        ]
    )


def _build_stack(cfg, runtime, name: str):
    import gymnasium as gym

    venv = ig.make_vector_env(cfg, N_ENVS, cfg.seed, device=runtime.device)
    space = venv.single_action_space
    is_continuous = isinstance(space, gym.spaces.Box)
    actions_dim = tuple(space.shape) if is_continuous else (int(space.n),)
    agent, params, player = build_agent(
        runtime, actions_dim, is_continuous, cfg, venv.single_observation_space, None
    )
    player.params = jax.device_put(player.params, runtime.device)
    venv.reset(seed=cfg.seed)
    collector = ig.InGraphRolloutCollector(
        venv, player, rollout_steps=T, gamma=float(cfg.algo.gamma), name=name
    )
    tx = with_clipping(instantiate(dict(cfg.algo.optimizer))(), cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    return venv, agent, params, player, collector, tx, opt_state


def _extras(cfg):
    return (jnp.float32(cfg.algo.clip_coef), jnp.float32(cfg.algo.ent_coef), jnp.float32(1.0))


def _base_hypers(cfg):
    return (float(cfg.algo.clip_coef), float(cfg.algo.ent_coef), 1.0)


def _pop_update_impl(cfg, runtime, agent, tx):
    # env-batch data sharding does not apply under the member vmap/shard_map,
    # and each member batches over its OWN rollout (the mesh shards members,
    # not data — batch_size must not scale with world_size)
    return make_update_impl(
        agent, tx, cfg, runtime, N_DATA, ["state"], [], None,
        constrain_data=False, batch_size=int(cfg.algo.per_rank_batch_size),
    )


@pytest.mark.timeout(300)
def test_population_of_one_matches_fused_bitwise():
    """K iterations of a 1-member population == K FusedInGraphTrainer steps,
    bit for bit: params, optimizer state, and the carried env chain."""
    cfg = _load_cfg("jax_cartpole")
    runtime = build_runtime(cfg.fabric)
    extras = _extras(cfg)
    K = 3

    # single-member fused reference
    venv_f, agent_f, params_f, player_f, coll_f, tx_f, opt_f = _build_stack(
        cfg, runtime, "pop1_fusedref"
    )
    upd_f = make_update_impl(
        agent_f, tx_f, cfg, runtime, N_DATA, ["state"], [], PlayerParamsSync(player_f.params)
    )
    trainer_f = ig.FusedInGraphTrainer(coll_f, upd_f, n_extras=3, name="pop1_fusedref")
    for i in range(K):
        key = jax.random.fold_in(jax.random.PRNGKey(99), i)
        params_f, opt_f, _flat, _roll, _train = trainer_f.step(params_f, opt_f, key, *extras)

    # population of one on a fresh identical world (same seed => same bits)
    venv_p, agent_p, params_p, _player_p, coll_p, tx_p, opt_p = _build_stack(
        cfg, runtime, "pop1_member"
    )
    pop = ig.PopulationTrainer(
        coll_p, _pop_update_impl(cfg, runtime, agent_p, tx_p),
        n_hypers=3, iters_per_epoch=K, name="pop1_member",
    )
    state = pop.init_population(params_p, opt_p, jax.random.PRNGKey(0), 1, _base_hypers(cfg))
    # pin member 0's env chain to the fused venv's reset carry (init_population
    # re-keys per member; bit-parity needs the identical starting streams)
    state = state._replace(carry=ig.stack_member(venv_p.carry, 1))
    iter_keys = jnp.stack(
        [jax.random.fold_in(jax.random.PRNGKey(99), i)[None] for i in range(K)]
    )
    state, last_roll, _train_ms = pop.epoch_fn(state, None, iter_keys)

    for pa, pb in zip(
        jax.tree_util.tree_leaves(params_f), jax.tree_util.tree_leaves(state.params)
    ):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb)[0])
    for oa, ob in zip(
        jax.tree_util.tree_leaves(opt_f), jax.tree_util.tree_leaves(state.opt_state)
    ):
        if np.shape(ob)[:1] == (1,):
            np.testing.assert_array_equal(np.asarray(oa), np.asarray(ob)[0])
    np.testing.assert_array_equal(
        np.asarray(venv_f.carry.obs), np.asarray(state.carry.obs)[0]
    )
    assert np.asarray(last_roll["dones"]).shape == (1, T, N_ENVS)

    # exploit on a population of one is the identity plan: the sole member is
    # its own top AND bottom quantile, never strictly fitter than itself
    _state2, member_src, factor = pop.exploit(state, jax.random.PRNGKey(42))
    assert np.asarray(member_src).tolist() == [0]
    np.testing.assert_array_equal(np.asarray(factor), 1.0)
    venv_f.close()
    venv_p.close()


def test_exploit_plan_matches_host_pbt_helpers():
    """The jax-traced plan reproduces ``resow.bottom_quantile`` selection
    (stable (fitness, index) ordering, ``max(int(n·q), 1)`` cut) and
    ``resow.perturb`` factor semantics (multiplicative draw from ``factors``,
    untouched keys stay at factor 1.0)."""
    factors = (0.8, 1.25)
    for n, q in ((8, 0.25), (5, 0.25), (4, 0.5), (3, 0.1)):
        fit = np.linspace(10.0, 10.0 + n - 1, n)[::-1].copy()  # distinct, reversed
        host = resow.bottom_quantile({f"m{i:02d}": float(fit[i]) for i in range(n)}, q)
        host_idx = sorted(int(k[1:]) for k in host)
        member_src, factor, swapped = ig.exploit_plan(
            jnp.asarray(fit, jnp.float32), jax.random.PRNGKey(0),
            quantile=q, n_hypers=3, factors=factors,
        )
        member_src, factor, swapped = map(np.asarray, (member_src, factor, swapped))
        # with distinct fitness every bottom member finds a strictly-fitter
        # parent, so the swapped set IS the host helper's bottom quantile
        assert sorted(np.nonzero(swapped)[0].tolist()) == host_idx, (n, q)
        # clone sources live in the top quantile and are strictly fitter
        n_cut = max(int(n * q), 1)
        top_idx = set(np.argsort(fit, kind="stable")[n - n_cut:].tolist())
        for i in host_idx:
            assert int(member_src[i]) in top_idx
            assert fit[int(member_src[i])] > fit[i]
        # perturb: swapped rows draw factors from the PBT set, others are 1.0
        assert np.all(np.isin(factor[swapped], np.asarray(factors, np.float32)))
        np.testing.assert_array_equal(factor[~swapped], 1.0)

    # ties at the cut break by member index — bottom_quantile's (fitness, key)
    fit = np.asarray([5.0, 1.0, 1.0, 9.0], np.float32)
    host = resow.bottom_quantile({f"m{i:02d}": float(fit[i]) for i in range(4)}, 0.25)
    assert host == ["m01"]
    _src, _fac, swapped = ig.exploit_plan(
        jnp.asarray(fit), jax.random.PRNGKey(1), quantile=0.25, n_hypers=1, factors=factors
    )
    assert np.nonzero(np.asarray(swapped))[0].tolist() == [1]

    # perturb_mask pins masked hyper columns at factor 1.0 even when swapped
    _src, factor, swapped = ig.exploit_plan(
        jnp.asarray([0.0, 1.0, 2.0, 3.0], jnp.float32), jax.random.PRNGKey(2),
        quantile=0.25, n_hypers=3, factors=factors, perturb_mask=(True, False, True),
    )
    factor = np.asarray(factor)
    np.testing.assert_array_equal(factor[:, 1], 1.0)
    assert np.all(np.isin(factor[np.asarray(swapped), 0], np.asarray(factors, np.float32)))


@pytest.mark.timeout(300)
def test_population_aot_warmup_zero_retrace():
    """Epoch + exploit AOT-compile from ``stacked_specs`` built off ONE
    member's live values — before the population exists — then run two
    epoch/exploit rounds live with zero retraces."""
    cfg = _load_cfg("jax_cartpole")
    runtime = build_runtime(cfg.fabric)
    venv, agent, params, _player, collector, tx, opt_state = _build_stack(
        cfg, runtime, "pop_warm"
    )
    pop = ig.PopulationTrainer(
        collector, _pop_update_impl(cfg, runtime, agent, tx),
        n_hypers=3, iters_per_epoch=2, name="pop_warm",
    )
    n = 4
    base = _base_hypers(cfg)
    ranges = ig.resolve_ranges(venv.env_params, cfg.env.id)
    overrides = ig.sample_overrides(jax.random.PRNGKey(3), n, ranges)

    warmup = jax_compile.AOTWarmup(enabled=True)
    warmup.add(pop.epoch_fn, *pop.stacked_warmup_specs(params, opt_state, base, n, overrides))
    warmup.add(pop.exploit_fn, *pop.stacked_exploit_specs(params, opt_state, base, n))
    warmup.start()
    state = pop.init_population(params, opt_state, jax.random.PRNGKey(1), n, base, overrides)
    assert warmup.wait(240), "population AOT warmup did not finish"

    for e in range(2):
        state, roll, _tms = pop.run_epoch(state, overrides, jax.random.fold_in(jax.random.PRNGKey(7), e))
        state, member_src, _factor = pop.exploit(state, jax.random.fold_in(jax.random.PRNGKey(8), e))
    assert pop.epoch_fn.retraces == 0, "population epoch retraced after AOT warmup"
    assert pop.exploit_fn.retraces == 0, "population exploit retraced after AOT warmup"
    assert np.asarray(roll["dones"]).shape == (n, T, N_ENVS)
    assert np.asarray(member_src).shape == (n,)
    assert np.all(np.isfinite(np.asarray(state.fitness)))
    venv.close()


@pytest.mark.timeout(300)
def test_population_sharded_eight_device_mesh():
    """The shard_map variant: 8 members across an 8-device mesh (member axis
    on ``data``, one member's full train loop per device), domain-randomized
    physics, in-graph exploit on the global sharded arrays — zero retraces."""
    if len(jax.local_devices()) < 8:
        pytest.skip("needs >= 8 local devices (conftest forces 8 on CPU)")
    cfg = _load_cfg("jax_cartpole", extra=["fabric.devices=8"])
    runtime = build_runtime(cfg.fabric)
    assert runtime.world_size == 8
    venv, agent, params, _player, collector, tx, opt_state = _build_stack(
        cfg, runtime, "pop_mesh"
    )
    pop = ig.PopulationTrainer(
        collector, _pop_update_impl(cfg, runtime, agent, tx),
        n_hypers=3, iters_per_epoch=2, mesh=runtime.mesh, name="pop_mesh",
    )
    n = 8
    base = _base_hypers(cfg)
    overrides = pop.commit_env_overrides(
        ig.sample_overrides(
            jax.random.PRNGKey(5), n, ig.resolve_ranges(venv.env_params, cfg.env.id)
        )
    )
    state = pop.init_population(params, opt_state, jax.random.PRNGKey(1), n, base, overrides)
    for e in range(2):
        state, roll, _tms = pop.run_epoch(state, overrides, jax.random.fold_in(jax.random.PRNGKey(7), e))
        state, member_src, _factor = pop.exploit(state, jax.random.fold_in(jax.random.PRNGKey(8), e))
    assert pop.epoch_fn.retraces == 0, "sharded population epoch retraced"
    assert pop.exploit_fn.retraces == 0, "sharded population exploit retraced"
    assert np.asarray(roll["dones"]).shape == (n, T, N_ENVS)
    assert np.asarray(state.fitness).shape == (n,)
    assert np.all(np.isfinite(np.asarray(state.fitness)))
    assert all(
        np.all(np.isfinite(np.asarray(x))) for x in jax.tree_util.tree_leaves(state.params)
    )
    venv.close()


def test_domain_rand_ranges_and_dynamics_divergence():
    """Default ranges resolve against the real EnvParams fields, bad configs
    are rejected up front, and a physics override genuinely changes the traced
    dynamics (same state + action, different gravity => different next state)."""
    env, params = ig.make("CartPole-v1")
    ranges = ig.resolve_ranges(params, "CartPole-v1")
    assert set(ranges) == {"gravity", "masscart", "masspole", "length"}
    overrides = ig.sample_overrides(jax.random.PRNGKey(0), 6, ranges)
    for name, (lo, hi) in ranges.items():
        vals = np.asarray(overrides[name])
        assert vals.shape == (6,)
        assert np.all((vals >= lo) & (vals <= hi))
    # per-member draws actually differ
    assert len(np.unique(np.asarray(overrides["gravity"]))) > 1

    with pytest.raises(ValueError, match="not a dynamics field"):
        ig.resolve_ranges(params, None, {"warp_factor": (1.0, 2.0)})
    with pytest.raises(ValueError, match="not a dynamics field"):
        ig.resolve_ranges(params, None, {"max_episode_steps": (100, 200)})
    with pytest.raises(ValueError, match="bad range"):
        ig.resolve_ranges(params, None, {"gravity": (11.0, 8.0)})
    assert ig.sample_overrides(jax.random.PRNGKey(0), 4, {}) is None

    state, _obs = env.reset(jax.random.PRNGKey(9), params)
    action = jnp.int32(1)
    step = lambda p: env.step(jax.random.PRNGKey(10), state, action, p)
    s_lo, *_ = step(params.replace(gravity=8.0))
    s_hi, *_ = step(params.replace(gravity=11.5))
    assert not np.array_equal(np.asarray(s_lo.y), np.asarray(s_hi.y))


@pytest.mark.faults
def test_population_failpoints_registered_and_fire():
    """Both population chaos seams are in the static registry and fire."""
    for name in ("population.exploit", "population.member_sync"):
        assert name in failpoints.KNOWN_FAILPOINTS
        assert failpoints.KNOWN_FAILPOINTS[name]["plane"] == "orchestrate"
    with failpoints.active("population.exploit:raise:chaos-pop"):
        with pytest.raises(failpoints.FailpointError, match="chaos-pop"):
            failpoints.failpoint("population.exploit", epoch=0)
    with failpoints.active("population.member_sync:fire"):
        assert failpoints.failpoint("population.member_sync", member=1) is True
    assert failpoints.failpoint("population.member_sync", member=1) is not True
