"""ReplayRing (envs/ingraph/replay_ring.py): the donated HBM transition store
for the fused off-policy path.

Pins the three contracts the fused SAC iteration leans on: block writes wrap
the cursor with the same overwrite semantics as sequential single-row writes,
sampling is uniform over exactly the ``filled * B`` valid transitions (seam
included), and both write and sample behave identically eager and under jit —
they run INSIDE the fused program, so any host-side divergence would silently
fork training from what the unit tests check.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.envs.ingraph.replay_ring import ReplayRing

pytestmark = pytest.mark.ingraph

N_ENVS = 3


def _ring(capacity: int = 4) -> ReplayRing:
    return ReplayRing(
        capacity, N_ENVS, {"obs": ((2,), jnp.float32), "rew": ((1,), jnp.float32)}
    )


def _rows(first_val: int, t: int):
    """A [t, B, ...] block whose every element equals its global write index,
    so ring contents identify exactly which writes survived."""
    vals = jnp.arange(first_val, first_val + t, dtype=jnp.float32)
    return {
        "obs": jnp.broadcast_to(vals[:, None, None], (t, N_ENVS, 2)),
        "rew": jnp.broadcast_to(vals[:, None, None], (t, N_ENVS, 1)),
    }


def _row_vals(state) -> np.ndarray:
    """One scalar per ring row (rows are constant blocks by construction)."""
    return np.asarray(state.data["obs"])[:, 0, 0]


def test_write_fills_then_wraps():
    ring = _ring(capacity=4)
    state = ring.init_state()
    assert int(state.filled) == 0

    state = ring.write(state, _rows(0, 3))
    assert int(state.pos) == 3 and int(state.filled) == 3
    np.testing.assert_array_equal(_row_vals(state), [0.0, 1.0, 2.0, 0.0])

    state = ring.write(state, _rows(3, 3))
    # rows 3,4,5 land at slots 3,0,1 — the two oldest rows are overwritten
    assert int(state.pos) == 2 and int(state.filled) == 4
    np.testing.assert_array_equal(_row_vals(state), [4.0, 5.0, 2.0, 3.0])


def test_oversize_block_write_matches_sequential_writes():
    ring = _ring(capacity=4)
    blocked = ring.write(ring.init_state(), _rows(0, 6))
    sequential = ring.init_state()
    for i in range(6):
        sequential = ring.write(sequential, _rows(i, 1))
    assert int(blocked.pos) == int(sequential.pos)
    assert int(blocked.filled) == int(sequential.filled)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        blocked.data,
        sequential.data,
    )


def test_sample_draws_only_valid_rows_before_wrap():
    ring = _ring(capacity=8)
    state = ring.write(ring.init_state(), _rows(1, 2))  # rows 1,2 valid; 6 empty
    batch = ring.sample(state, jax.random.PRNGKey(0), 256)
    vals = np.asarray(batch["obs"])[:, 0]
    assert set(np.unique(vals)) == {1.0, 2.0}, "sampled an unwritten (zero) row"
    assert batch["obs"].shape == (256, 2) and batch["rew"].shape == (256, 1)


def test_sample_uniform_across_wraparound_seam():
    ring = _ring(capacity=4)
    state = ring.write(ring.init_state(), _rows(0, 6))  # valid rows hold 2..5
    vals = np.asarray(ring.sample(state, jax.random.PRNGKey(1), 4096)["obs"])[:, 0]
    counts = {v: int((vals == v).sum()) for v in (2.0, 3.0, 4.0, 5.0)}
    assert sum(counts.values()) == 4096, f"sampled overwritten rows: {np.unique(vals)}"
    # uniform within tolerance: each valid row should get ~1024 of 4096 draws
    assert min(counts.values()) > 700 and max(counts.values()) < 1400, counts


def test_sample_determinism_and_jit_parity():
    ring = _ring(capacity=4)
    state = ring.write(ring.init_state(), _rows(0, 4))
    key = jax.random.PRNGKey(7)
    eager_a = ring.sample(state, key, 32)
    eager_b = ring.sample(state, key, 32)
    jitted = jax.jit(partial(ring.sample, batch_size=32))(state, key)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        eager_a,
        eager_b,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        eager_a,
        jitted,
    )
    other = ring.sample(state, jax.random.PRNGKey(8), 32)
    assert not np.array_equal(np.asarray(eager_a["obs"]), np.asarray(other["obs"]))


def test_in_graph_write_then_sample_roundtrip():
    """The fused-iteration composition — donate the state, scatter a block,
    sample from the SAME program — works as one jitted function and matches
    the eager reference bit-for-bit."""
    ring = _ring(capacity=4)

    @partial(jax.jit, donate_argnums=(0,))
    def step(state, rows, key):
        state = ring.write(state, rows)
        return state, ring.sample(state, key, 16)

    key = jax.random.PRNGKey(3)
    eager_state = ring.write(ring.init_state(), _rows(0, 3))
    eager_batch = ring.sample(eager_state, key, 16)
    jit_state, jit_batch = step(ring.init_state(), _rows(0, 3), key)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        (eager_state, eager_batch),
        (jit_state, jit_batch),
    )


def test_capacity_validation():
    with pytest.raises(ValueError, match="capacity"):
        ReplayRing(0, 2, {"obs": ((1,), jnp.float32)})
