"""Env adapter tests.

The third-party env packages (crafter, dm_control, minedojo, minerl, diambra,
gym_super_mario_bros) are NOT installed in CI, so these tests check (a) the
import gating raises a clear ModuleNotFoundError, and (b) the conversion logic
via faked dependency modules (the same strategy works for any adapter whose
inner env is mocked).
"""

import importlib
import sys
import types

import gymnasium as gym
import numpy as np
import pytest

import sheeprl_tpu.utils.imports as imports_mod

_GATED_MODULES = {
    "sheeprl_tpu.envs.crafter": "_IS_CRAFTER_AVAILABLE",
    "sheeprl_tpu.envs.dmc": "_IS_DMC_AVAILABLE",
    "sheeprl_tpu.envs.diambra": "_IS_DIAMBRA_AVAILABLE",
    "sheeprl_tpu.envs.minedojo": "_IS_MINEDOJO_AVAILABLE",
    "sheeprl_tpu.envs.minerl": "_IS_MINERL_AVAILABLE",
    "sheeprl_tpu.envs.super_mario_bros": "_IS_SUPER_MARIO_AVAILABLE",
}


@pytest.mark.parametrize("module,flag", sorted(_GATED_MODULES.items()))
def test_adapters_gate_on_missing_deps(module, flag):
    if getattr(imports_mod, flag):
        pytest.skip(f"{flag} dependency installed; gating not exercised")
    sys.modules.pop(module, None)
    with pytest.raises(ModuleNotFoundError, match="is not installed"):
        importlib.import_module(module)


@pytest.fixture()
def fake_crafter(monkeypatch):
    """Minimal crafter stand-in to exercise the adapter's conversion logic."""

    class FakeEnv:  # deliberately NOT a gymnasium.Env: real crafter.Env is a
        # plain old-gym-style class, and the adapters must cope (gymnasium 1.x
        # gym.Wrapper would assert on it)
        def __init__(self, size=(64, 64), seed=None, reward=True):
            self.size = size
            self.reward_enabled = reward
            self.observation_space = gym.spaces.Box(0, 255, (*size, 3), np.uint8)
            self.action_space = gym.spaces.Discrete(17)
            self.reward_range = (-1.0, 1.0)
            self._seed = seed
            self._t = 0

        def step(self, action):
            self._t += 1
            obs = np.zeros((*self.size, 3), np.uint8)
            # terminate at step 2 with discount 0 (death), truncate at 3
            if self._t == 2:
                return obs, 1.0, True, {"discount": 0.0}
            if self._t >= 3:
                return obs, 0.5, True, {"discount": 1.0}
            return obs, 0.0, False, {"discount": 1.0}

        def reset(self):
            self._t = 0
            return np.zeros((*self.size, 3), np.uint8)

        def render(self):
            return np.zeros((*self.size, 3), np.uint8)

    mod = types.ModuleType("crafter")
    mod.Env = FakeEnv
    monkeypatch.setitem(sys.modules, "crafter", mod)
    monkeypatch.setattr(imports_mod, "_IS_CRAFTER_AVAILABLE", True)
    sys.modules.pop("sheeprl_tpu.envs.crafter", None)
    yield importlib.import_module("sheeprl_tpu.envs.crafter")
    sys.modules.pop("sheeprl_tpu.envs.crafter", None)


def test_crafter_wrapper_contract(fake_crafter):
    env = fake_crafter.CrafterWrapper("crafter_reward", 64, seed=3)
    assert isinstance(env.observation_space, gym.spaces.Dict)
    assert env.observation_space["rgb"].shape == (64, 64, 3)
    obs, info = env.reset()
    assert set(obs) == {"rgb"}
    _, _, terminated, truncated, _ = env.step(0)
    assert not terminated and not truncated
    # discount 0 => terminated (death), not truncated
    _, _, terminated, truncated, _ = env.step(0)
    assert terminated and not truncated


def test_crafter_wrapper_rejects_unknown_id(fake_crafter):
    with pytest.raises(ValueError, match="Unknown crafter id"):
        fake_crafter.CrafterWrapper("crafter_bogus", 64)


def test_crafter_truncates_on_time_limit(fake_crafter):
    env = fake_crafter.CrafterWrapper("crafter_reward", 64)
    env.reset()
    env.env._t = 2  # next step hits the t>=3 branch: done with discount 1
    _, _, terminated, truncated, _ = env.step(0)
    assert truncated and not terminated


@pytest.fixture()
def fake_dmc(monkeypatch):
    """Fake dm_control/dm_env spec machinery for the pure helpers."""

    class Array:
        def __init__(self, shape, dtype=np.float64):
            self.shape = shape
            self.dtype = dtype

    class BoundedArray(Array):
        def __init__(self, shape, minimum, maximum, dtype=np.float64):
            super().__init__(shape, dtype)
            self.minimum = minimum
            self.maximum = maximum

    specs_mod = types.ModuleType("dm_env.specs")
    specs_mod.Array = Array
    specs_mod.BoundedArray = BoundedArray
    dm_env_mod = types.ModuleType("dm_env")
    dm_env_mod.specs = specs_mod
    dm_control_mod = types.ModuleType("dm_control")
    dm_control_mod.suite = types.ModuleType("dm_control.suite")
    monkeypatch.setitem(sys.modules, "dm_env", dm_env_mod)
    monkeypatch.setitem(sys.modules, "dm_env.specs", specs_mod)
    monkeypatch.setitem(sys.modules, "dm_control", dm_control_mod)
    monkeypatch.setitem(sys.modules, "dm_control.suite", dm_control_mod.suite)
    monkeypatch.setattr(imports_mod, "_IS_DMC_AVAILABLE", True)
    sys.modules.pop("sheeprl_tpu.envs.dmc", None)
    yield importlib.import_module("sheeprl_tpu.envs.dmc"), specs_mod
    sys.modules.pop("sheeprl_tpu.envs.dmc", None)


def test_dmc_spec_to_box(fake_dmc):
    dmc, specs = fake_dmc
    box = dmc._spec_to_box(
        [specs.BoundedArray((2,), -1.0, 1.0), specs.Array((3,))], np.float32
    )
    assert box.shape == (5,)
    np.testing.assert_allclose(box.low[:2], -1.0)
    assert np.isinf(box.low[2:]).all()


def test_dmc_flatten_obs(fake_dmc):
    dmc, _ = fake_dmc
    flat = dmc._flatten_obs({"a": np.ones((2, 2)), "b": 3.0})
    np.testing.assert_allclose(flat, [1, 1, 1, 1, 3])


@pytest.fixture()
def fake_minedojo(monkeypatch):
    """Fake minedojo item tables so the adapter module imports; the action
    conversion logic is then testable without a real env (via __new__)."""
    items = ["air", "stone", "wood"]
    sim_mod = types.ModuleType("minedojo.sim")
    sim_mod.ALL_ITEMS = items
    sim_mod.ALL_CRAFT_SMELT_ITEMS = ["planks"]
    tasks_mod = types.ModuleType("minedojo.tasks")
    tasks_mod.ALL_TASKS_SPECS = {}
    minedojo_mod = types.ModuleType("minedojo")
    minedojo_mod.sim = sim_mod
    minedojo_mod.tasks = tasks_mod
    minedojo_mod.make = lambda **kw: None
    monkeypatch.setitem(sys.modules, "minedojo", minedojo_mod)
    monkeypatch.setitem(sys.modules, "minedojo.sim", sim_mod)
    monkeypatch.setitem(sys.modules, "minedojo.tasks", tasks_mod)
    monkeypatch.setattr(imports_mod, "_IS_MINEDOJO_AVAILABLE", True)
    sys.modules.pop("sheeprl_tpu.envs.minedojo", None)
    yield importlib.import_module("sheeprl_tpu.envs.minedojo")
    sys.modules.pop("sheeprl_tpu.envs.minedojo", None)


def _bare_minedojo_wrapper(mod, sticky_attack=30, sticky_jump=10):
    w = mod.MineDojoWrapper.__new__(mod.MineDojoWrapper)
    w._sticky_attack = sticky_attack
    w._sticky_jump = sticky_jump
    w._sticky_attack_counter = 0
    w._sticky_jump_counter = 0
    w._inventory = {"stone": [5]}
    return w


def test_minedojo_sticky_attack_repeats(fake_minedojo):
    w = _bare_minedojo_wrapper(fake_minedojo)
    attack = w._convert_action(np.array([14, 0, 0]))
    assert attack[5] == 3 and w._sticky_attack_counter == 29
    # a no-op keeps attacking while the counter runs
    noop = w._convert_action(np.array([0, 0, 0]))
    assert noop[5] == 3 and w._sticky_attack_counter == 28
    # another functional action cancels the stick
    use = w._convert_action(np.array([12, 0, 0]))
    assert use[5] == 1 and w._sticky_attack_counter == 0


def test_minedojo_sticky_jump_moves_forward(fake_minedojo):
    w = _bare_minedojo_wrapper(fake_minedojo)
    jump = w._convert_action(np.array([5, 0, 0]))
    assert jump[2] == 1 and w._sticky_jump_counter == 9
    noop = w._convert_action(np.array([0, 0, 0]))
    # the sticky jump keeps jumping AND pushes forward
    assert noop[2] == 1 and noop[0] == 1 and w._sticky_jump_counter == 8


def test_minedojo_craft_and_destroy_args(fake_minedojo):
    w = _bare_minedojo_wrapper(fake_minedojo, sticky_attack=0, sticky_jump=0)
    craft = w._convert_action(np.array([15, 7, 0]))
    assert craft[5] == 4 and craft[6] == 7  # craft target forwarded
    destroy = w._convert_action(np.array([18, 0, 1]))  # item 1 = "stone"
    assert destroy[5] == 7 and destroy[7] == 5  # resolved to inventory slot 5


def test_minedojo_actor_masked_sampling():
    """The MinedojoActor vetoes masked macros and conditions the target heads on
    the sampled functional action (reference dreamer_v3/agent.py:883-934)."""
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v3.agent import MinedojoActor, sample_minedojo_actions

    actor = MinedojoActor(
        latent_state_size=8,
        actions_dim=(19, 4, 6),
        is_continuous=False,
        dense_units=8,
        mlp_layers=1,
    )
    params = actor.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
    pre_dist = actor.apply(params, jnp.zeros((3, 8)))
    mask = {
        # only macro 15 (craft) allowed => functional action must be 15
        "mask_action_type": jnp.zeros((3, 19), bool).at[:, 15].set(True),
        # only craft target 2 allowed
        "mask_craft_smelt": jnp.zeros((3, 4), bool).at[:, 2].set(True),
        "mask_equip_place": jnp.ones((3, 6), bool),
        "mask_destroy": jnp.ones((3, 6), bool),
    }
    actions = sample_minedojo_actions(actor, pre_dist, mask, jax.random.PRNGKey(1))
    assert (actions[0].argmax(-1) == 15).all()
    assert (actions[1].argmax(-1) == 2).all()  # craft head masked because macro==15


def test_minedojo_actor_dv2_masked_sampling_and_exploration():
    """DV2-level MineDojo actor: masked sampling + mask-respecting exploration
    noise (reference dreamer_v2/agent.py:626-776)."""
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v2.agent import MinedojoActorDV2, add_exploration_noise_minedojo

    actor = MinedojoActorDV2(
        latent_state_size=8,
        actions_dim=(19, 4, 6),
        is_continuous=False,
        dense_units=8,
        mlp_layers=1,
    )
    assert actor.uses_action_mask
    params = actor.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
    pre_dist = actor.apply(params, jnp.zeros((3, 8)))
    mask = {
        "mask_action_type": jnp.zeros((3, 19), bool).at[:, 15].set(True),
        "mask_craft_smelt": jnp.zeros((3, 4), bool).at[:, 2].set(True),
        "mask_equip_place": jnp.ones((3, 6), bool),
        "mask_destroy": jnp.ones((3, 6), bool),
    }
    actions = actor.sample(pre_dist, jax.random.PRNGKey(1), mask=mask)
    assert (actions[0].argmax(-1) == 15).all()
    assert (actions[1].argmax(-1) == 2).all()

    # exploration with amount=1 must still respect the masks: every exploratory
    # macro is 15 and every exploratory craft target is 2
    expl = add_exploration_noise_minedojo(actions, jnp.float32(1.0), jax.random.PRNGKey(2), mask)
    assert (expl[0].argmax(-1) == 15).all()
    assert (expl[1].argmax(-1) == 2).all()
    # amount=0 leaves the actions untouched
    same = add_exploration_noise_minedojo(actions, jnp.float32(0.0), jax.random.PRNGKey(3), mask)
    for a, b in zip(actions, same):
        assert (jnp.asarray(a) == jnp.asarray(b)).all()


@pytest.mark.skipif(not imports_mod._IS_DMC_AVAILABLE, reason="dm_control not installed")
def test_dmc_wrapper_real_env(monkeypatch):
    """dm_control is present in the image: exercise the real adapter (headless EGL)."""
    reason = imports_mod.dmc_render_unusable_reason()
    if reason is not None:
        pytest.skip(reason)
    monkeypatch.setenv("MUJOCO_GL", "egl")
    sys.modules.pop("sheeprl_tpu.envs.dmc", None)
    dmc = importlib.import_module("sheeprl_tpu.envs.dmc")
    env = dmc.DMCWrapper("cartpole", "balance", from_pixels=True, from_vectors=True, height=32, width=32)
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (3, 32, 32) and obs["rgb"].dtype == np.uint8
    assert obs["state"].shape == env.state_space.shape
    action = env.action_space.sample()
    obs, reward, terminated, truncated, info = env.step(action)
    assert "discount" in info and not terminated
    assert env.action_space.low.min() == -1.0 and env.action_space.high.max() == 1.0
