"""PixelTargetEnv: dynamics, reward shaping, and factory integration."""

import numpy as np
import pytest

from sheeprl_tpu.envs.pixel_control import PixelTargetEnv


def _greedy_action(env) -> int:
    dy, dx = env._target - env._agent
    if abs(dy) >= abs(dx):
        return 2 if dy > 0 else 1
    return 4 if dx > 0 else 3


def test_spaces_and_obs():
    env = PixelTargetEnv(seed=0)
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (3, 64, 64)
    assert obs["rgb"].dtype == np.uint8
    assert env.action_space.n == 5
    # both squares are drawn: white agent (all channels) and red target
    assert (obs["rgb"] == 255).any()
    assert (obs["rgb"][0].astype(int) - obs["rgb"][1].astype(int) == 255).any()


def test_greedy_policy_reaches_target_every_episode():
    env = PixelTargetEnv(seed=1)
    for ep in range(10):
        env.reset()
        for _ in range(100):
            _, r, term, trunc, _ = env.step(_greedy_action(env))
            if term or trunc:
                break
        assert term and r == 1.0, f"episode {ep} did not terminate at the target"


def test_shaping_rewards_progress():
    env = PixelTargetEnv(seed=2)
    env.reset()
    toward = _greedy_action(env)
    away = {1: 2, 2: 1, 3: 4, 4: 3}[toward]
    _, r_away, *_ = env.step(away)
    _, r_toward, *_ = env.step(toward)
    assert r_toward > r_away


def test_truncation_at_horizon():
    env = PixelTargetEnv(seed=3, max_steps=5)
    env.reset()
    for t in range(5):
        _, _, term, trunc, _ = env.step(0)  # noop never reaches (spawn is far)
    assert trunc and not term


def test_make_env_factory():
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.utils.env import make_env

    cfg = compose(config_name="config", overrides=["exp=dreamer_v3_pixel_target", "env.capture_video=False"])
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset(seed=0)
    assert "rgb" in obs and obs["rgb"].shape == (3, 64, 64)
    out = env.step(env.action_space.sample())
    assert len(out) == 5
    env.close()


def test_degenerate_geometry_rejected():
    with pytest.raises(ValueError, match="quarter"):
        PixelTargetEnv(size=8, block=8)  # no free space at all
    with pytest.raises(ValueError, match="quarter"):
        PixelTargetEnv(size=64, block=58)  # free space < required separation
