"""PPOPlayer.act_raw must be bit-identical to the prepare_obs + __call__ path
(the rollout loops use act_raw for one-dispatch stepping; eval/bootstrap paths
still go through prepare_obs)."""

import gymnasium as gym
import jax
import numpy as np

from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.utils import prepare_obs
from sheeprl_tpu.config.loader import load_config
from sheeprl_tpu.core.runtime import Runtime


def test_act_raw_matches_prepare_obs_path():
    cfg = load_config(
        overrides=[
            "exp=ppo",
            "env=dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.cnn_features_dim=16",
            "algo.encoder.mlp_features_dim=8",
        ]
    )
    runtime = Runtime(accelerator="cpu", devices=1)
    obs_space = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8),
            "state": gym.spaces.Box(-1, 1, (4,), np.float32),
        }
    )
    _agent, _params, player = build_agent(runtime, (3,), False, cfg, obs_space)

    n_envs = 2
    rng = np.random.default_rng(0)
    raw = {
        "rgb": rng.integers(0, 255, (n_envs, 3, 64, 64)).astype(np.uint8),
        "state": rng.standard_normal((n_envs, 4)).astype(np.float32),
    }
    key = jax.device_put(jax.random.PRNGKey(7), runtime.player_device)

    prepped = prepare_obs(runtime, raw, cnn_keys=["rgb"], num_envs=n_envs)
    old = player(prepped, key)
    new = player.act_raw(raw, key)
    for a, b in zip(old[:4], new[:4]):
        # host-numpy vs in-graph normalization differ by float rounding only
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)

    # frame-stacked cnn obs [n_envs, S, C, H, W] collapse to channels in-graph
    stacked = dict(raw)
    stacked["rgb"] = np.repeat(raw["rgb"][:, None], 2, axis=1)  # [n_envs, 2, 3, 64, 64]
    prepped_stacked = prepare_obs(runtime, stacked, cnn_keys=["rgb"], num_envs=n_envs)
    # need an agent built for 6 input channels
    obs_space6 = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (2, 3, 64, 64), np.uint8),
            "state": gym.spaces.Box(-1, 1, (4,), np.float32),
        }
    )
    _agent6, _params6, player6 = build_agent(runtime, (3,), False, cfg, obs_space6)
    old6 = player6(prepped_stacked, key)
    new6 = player6.act_raw(stacked, key)
    for a, b in zip(old6[:4], new6[:4]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_recurrent_act_raw_matches_prepare_obs_path():
    """Same pin for RecurrentPPOPlayer.act_raw: the recurrent rollout loop now
    uses it exclusively, so its in-graph normalization + T=1 expansion must
    track the prepare_obs path (including carried LSTM states)."""
    from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent as build_recurrent

    cfg = load_config(
        overrides=[
            "exp=ppo_recurrent",
            "env=dummy",
            "env.num_envs=2",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.cnn_features_dim=16",
            "algo.encoder.mlp_features_dim=8",
            "algo.rnn.lstm.hidden_size=8",
        ]
    )
    runtime = Runtime(accelerator="cpu", devices=1)
    obs_space = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8),
            "state": gym.spaces.Box(-1, 1, (4,), np.float32),
        }
    )
    n_envs = 2
    _agent, _params, player = build_recurrent(runtime, (3,), False, cfg, obs_space)

    rng = np.random.default_rng(1)
    raw = {
        "rgb": rng.integers(0, 255, (n_envs, 3, 64, 64)).astype(np.uint8),
        "state": rng.standard_normal((n_envs, 4)).astype(np.float32),
    }
    prev_actions = np.zeros((n_envs, 3), np.float32)
    prev_states = player.initial_states(8)
    key = jax.device_put(jax.random.PRNGKey(11), runtime.player_device)

    prepped = prepare_obs(runtime, raw, cnn_keys=["rgb"], num_envs=n_envs)
    prepped = {k: v[None] for k, v in prepped.items()}
    old = player(prepped, jax.device_put(prev_actions[None], runtime.player_device), prev_states, key)
    new = player.act_raw(raw, prev_actions, prev_states, key)
    for a, b in zip(jax.tree_util.tree_leaves(old[:5]), jax.tree_util.tree_leaves(new[:5])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
