"""DreamerV3 RSSM unit tests.

Regression focus: `dynamic_scan` must return *factorized* prior/posterior logits
``[T, B, stoch, discrete]`` — the KL-balance loss softmaxes per categorical over the
discrete dim (reference sheeprl/algos/dreamer_v3/loss.py via
torch.distributions.Independent(OneHotCategorical)); flat ``[T, B, S*D]`` logits
would silently compute one big softmax and reduce over the batch axis too
(only broadcastable — hence undetected — at T==1).
"""

import jax
import jax.numpy as jnp
import pytest

from sheeprl_tpu.algos.dreamer_v3.agent import MLPWithHead, RSSM, RecurrentModel
from sheeprl_tpu.algos.dreamer_v3.loss import categorical_kl, reconstruction_loss

KEY = jax.random.PRNGKey(0)

S, D, R, E, A = 3, 4, 8, 6, 2


def _make_rssm(decoupled: bool = False):
    rec = RecurrentModel(input_size=S * D + A, recurrent_state_size=R, dense_units=8)
    repr_in = E if decoupled else R + E
    repr_m = MLPWithHead(input_dim=repr_in, hidden_sizes=[8], output_dim=S * D)
    trans = MLPWithHead(input_dim=R, hidden_sizes=[8], output_dim=S * D)
    rssm = RSSM(rec, repr_m, trans, stochastic_size=S, discrete_size=D, decoupled=decoupled)
    wm_params = {
        "recurrent_model": rec.init(KEY, jnp.zeros((1, S * D + A)), jnp.zeros((1, R))),
        "representation_model": repr_m.init(KEY, jnp.zeros((1, repr_in))),
        "transition_model": trans.init(KEY, jnp.zeros((1, R))),
        "initial_recurrent_state": jnp.zeros((R,), dtype=jnp.float32),
    }
    return rssm, wm_params


@pytest.mark.parametrize("decoupled", [False, True])
def test_dynamic_scan_returns_factorized_logits(decoupled):
    rssm, wm_params = _make_rssm(decoupled)
    T, B = 5, 3
    embedded = jax.random.normal(jax.random.PRNGKey(1), (T, B, E))
    actions = jnp.zeros((T, B, A))
    is_first = jnp.zeros((T, B, 1)).at[0].set(1.0)
    rec_states, posteriors, priors_logits, posteriors_logits = rssm.dynamic_scan(
        wm_params, embedded, actions, is_first, KEY
    )
    assert rec_states.shape == (T, B, R)
    assert posteriors.shape == (T, B, S, D)
    assert priors_logits.shape == (T, B, S, D)
    assert posteriors_logits.shape == (T, B, S, D)
    # KL must stay per-element [T, B] for T > 1 (the T==1 broadcast masked this)
    kl = categorical_kl(posteriors_logits, priors_logits)
    assert kl.shape == (T, B)
    assert bool(jnp.all(kl >= -1e-6))


def test_reconstruction_loss_elementwise_at_t_gt_1():
    rssm, wm_params = _make_rssm()
    T, B = 4, 2
    embedded = jax.random.normal(jax.random.PRNGKey(2), (T, B, E))
    actions = jnp.zeros((T, B, A))
    is_first = jnp.zeros((T, B, 1)).at[0].set(1.0)
    _, _, priors_logits, posteriors_logits = rssm.dynamic_scan(
        wm_params, embedded, actions, is_first, KEY
    )
    po = {"state": jnp.zeros((T, B))}
    loss, kl, state_loss, reward_loss, obs_loss, cont_loss = reconstruction_loss(
        po,
        jnp.zeros((T, B)),
        priors_logits,
        posteriors_logits,
        pc_log_prob=jnp.zeros((T, B)),
    )
    for v in (loss, kl, state_loss, reward_loss, obs_loss, cont_loss):
        assert v.shape == ()
    assert jnp.isfinite(loss)


def test_imagination_step_shapes():
    rssm, wm_params = _make_rssm()
    B = 6
    prior_flat = jnp.zeros((B, S * D))
    rec_state = jnp.zeros((B, R))
    act = jnp.zeros((B, A))
    prior, rec = rssm.imagination_step(wm_params, prior_flat, rec_state, act, KEY)
    assert prior.shape == (B, S * D)
    assert rec.shape == (B, R)
    # one-hot per categorical
    assert jnp.allclose(prior.reshape(B, S, D).sum(-1), 1.0)


def test_dv3_actor_raw_samples_contract():
    """sample_actions_with_raw: the env/dynamics consume CLIPPED actions, the
    score-function estimator evaluates log-prob at the RAW samples (clipping
    rescales saturated continuous samples onto the boundary, where log-prob is
    not the sampled policy's score — benchmarks/WALKER_WALK_NOTES.md)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.algos.dreamer_v3.agent import Actor, ActorOutput

    actor = Actor(
        latent_state_size=8,
        actions_dim=(3,),
        is_continuous=True,
        distribution="auto",
        dense_units=8,
        mlp_layers=1,
    )
    latent = jnp.linspace(-3, 3, 2 * 8).reshape(2, 8)
    params = actor.init(jax.random.PRNGKey(0), latent)
    out = ActorOutput(actor, actor.apply(params, latent))
    (clipped,), (raw,) = out.sample_actions_with_raw(jax.random.PRNGKey(1))
    assert clipped.shape == raw.shape == (2, 3)
    # clipped action is the clip-rescaled raw sample; inside the box they agree
    np.testing.assert_allclose(
        np.asarray(clipped), np.clip(np.asarray(raw), -1.0, 1.0) * 0 + np.asarray(raw) * np.minimum(1.0, 1.0 / np.abs(np.asarray(raw))), rtol=1e-5
    )
    assert np.all(np.abs(np.asarray(clipped)) <= 1.0 + 1e-6)
    # sample_actions returns exactly the clipped list
    (via_plain,) = ActorOutput(actor, actor.apply(params, latent)).sample_actions(jax.random.PRNGKey(1))
    np.testing.assert_allclose(np.asarray(via_plain), np.asarray(clipped), rtol=1e-6)
    # discrete: raw == clipped (one-hot samples)
    dactor = Actor(
        latent_state_size=8, actions_dim=(4,), is_continuous=False, distribution="auto",
        dense_units=8, mlp_layers=1,
    )
    dparams = dactor.init(jax.random.PRNGKey(0), latent)
    dout = ActorOutput(dactor, dactor.apply(dparams, latent))
    (dc,), (dr,) = dout.sample_actions_with_raw(jax.random.PRNGKey(2))
    np.testing.assert_array_equal(np.asarray(dc), np.asarray(dr))
