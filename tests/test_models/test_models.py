import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.models.models import (
    CNN,
    DeCNN,
    MLP,
    LayerNorm,
    LayerNormChannelLast,
    LayerNormGRUCell,
    MultiDecoder,
    MultiEncoder,
    NatureCNN,
    get_activation,
)

KEY = jax.random.PRNGKey(0)


def test_mlp_shapes():
    m = MLP(input_dims=10, output_dim=5, hidden_sizes=(32, 32), activation="tanh")
    params = m.init(KEY, jnp.ones((2, 10)))
    out = m.apply(params, jnp.ones((2, 10)))
    assert out.shape == (2, 5)
    assert m.out_features == 5


def test_mlp_no_output_head():
    m = MLP(input_dims=4, hidden_sizes=(16,))
    params = m.init(KEY, jnp.ones((3, 4)))
    assert m.apply(params, jnp.ones((3, 4))).shape == (3, 16)
    assert m.out_features == 16


def test_mlp_requires_layers():
    m = MLP(input_dims=4)
    with pytest.raises(ValueError):
        m.init(KEY, jnp.ones((1, 4)))


def test_mlp_flatten():
    m = MLP(input_dims=(2, 3), hidden_sizes=(8,), flatten_dim=1)
    params = m.init(KEY, jnp.ones((5, 2, 3)))
    assert m.apply(params, jnp.ones((5, 2, 3))).shape == (5, 8)


def test_mlp_layer_norm_dtype_preserved():
    m = MLP(input_dims=4, hidden_sizes=(8,), layer_norm=True, dtype=jnp.bfloat16)
    params = m.init(KEY, jnp.ones((2, 4)))
    out = m.apply(params, jnp.ones((2, 4), dtype=jnp.bfloat16))
    assert out.dtype == jnp.bfloat16


@pytest.mark.parametrize("k,s,p", [(3, 1, 0), (4, 2, 1), (8, 4, 0)])
def test_cnn_shape_matches_torch_formula(k, s, p):
    m = CNN(input_channels=3, hidden_channels=[8], layer_args={"kernel_size": k, "stride": s, "padding": p})
    params = m.init(KEY, jnp.ones((1, 3, 64, 64)))
    out = m.apply(params, jnp.ones((2, 3, 64, 64)))
    expected = (64 + 2 * p - k) // s + 1
    assert out.shape == (2, 8, expected, expected)


@pytest.mark.parametrize("k,s,p,op", [(4, 2, 1, 0), (5, 2, 0, 0), (6, 2, 1, 0)])
def test_decnn_shape_matches_torch(k, s, p, op):
    import torch

    ref = torch.nn.ConvTranspose2d(4, 8, kernel_size=k, stride=s, padding=p, output_padding=op)
    expected = ref(torch.zeros(1, 4, 8, 8)).shape[-1]
    m = DeCNN(
        input_channels=4,
        hidden_channels=[8],
        layer_args={"kernel_size": k, "stride": s, "padding": p, "output_padding": op},
    )
    params = m.init(KEY, jnp.ones((1, 4, 8, 8)))
    out = m.apply(params, jnp.ones((2, 4, 8, 8)))
    assert out.shape == (2, 8, expected, expected)


def test_nature_cnn():
    m = NatureCNN(in_channels=4, features_dim=512, screen_size=64)
    params = m.init(KEY, jnp.ones((1, 4, 64, 64)))
    out = m.apply(params, jnp.ones((3, 4, 64, 64)))
    assert out.shape == (3, 512)


def test_layer_norm_gru_cell_math():
    cell = LayerNormGRUCell(hidden_size=4, layer_norm=False)
    x = jnp.ones((2, 3))
    h = jnp.zeros((2, 4))
    params = cell.init(KEY, x, h)
    out = cell.apply(params, x, h)
    assert out.shape == (2, 4)
    # replicate the gate math manually
    kernel = params["params"]["kernel"]
    bias = params["params"]["bias"]
    fused = jnp.concatenate([h, x], -1) @ kernel + bias
    reset, cand, update = jnp.split(fused, 3, -1)
    reset = jax.nn.sigmoid(reset)
    cand = jnp.tanh(reset * cand)
    update = jax.nn.sigmoid(update - 1)  # -1 update-gate bias (Hafner variant)
    expected = update * cand + (1 - update) * h
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5)


def test_layer_norm_gru_keeps_state_when_update_closed():
    cell = LayerNormGRUCell(hidden_size=8, layer_norm=True)
    x = jnp.zeros((1, 8))
    h = jax.random.normal(KEY, (1, 8))
    params = cell.init(KEY, x, h)
    out = cell.apply(params, x, h)
    assert out.shape == h.shape


def test_layer_norm_gru_ln_matches_numpy_reference():
    """Pin the LN-GRU gate math (Hafner variant: LN over the fused projection,
    reset*cand inside tanh, update bias -1 — reference models.py:396-403)
    against an independent numpy implementation."""
    cell = LayerNormGRUCell(hidden_size=16, layer_norm=True, bias=False)
    x = jax.random.normal(jax.random.PRNGKey(3), (5, 12))
    h = jax.random.normal(jax.random.PRNGKey(4), (5, 16))
    params = cell.init(KEY, x, h)
    out = cell.apply(params, x, h)

    p = params["params"]
    xh = np.concatenate([np.asarray(h), np.asarray(x)], axis=-1)
    z = xh @ np.asarray(p["kernel"], np.float64)
    mu = z.mean(-1, keepdims=True)
    var = z.var(-1, keepdims=True)
    z = (z - mu) / np.sqrt(var + 1e-5) * np.asarray(p["ln_scale"]) + np.asarray(p["ln_bias"])
    reset, cand, update = np.split(z, 3, axis=-1)
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    reset = sig(reset)
    cand = np.tanh(reset * cand)
    update = sig(update - 1)
    ref = update * cand + (1 - update) * np.asarray(h)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_layer_norm_channel_last():
    ln = LayerNormChannelLast()
    x = jax.random.normal(KEY, (2, 3, 4, 4), dtype=jnp.float32)
    params = ln.init(KEY, x)
    out = ln.apply(params, x)
    assert out.shape == x.shape
    # normalized over channels: per-pixel mean ~ 0
    np.testing.assert_allclose(np.asarray(out.mean(axis=1)), 0.0, atol=1e-5)
    with pytest.raises(ValueError):
        ln.apply(params, jnp.ones((2, 3, 4)))


def test_multi_encoder_concat():
    class FakeCNN(jnp.ndarray.__class__):
        pass

    import flax.linen as nn

    class CnnEnc(nn.Module):
        @nn.compact
        def __call__(self, obs):
            return jnp.ones((obs["rgb"].shape[0], 4))

    class MlpEnc(nn.Module):
        @nn.compact
        def __call__(self, obs):
            return jnp.ones((obs["state"].shape[0], 3))

    enc = MultiEncoder(cnn_encoder=CnnEnc(), mlp_encoder=MlpEnc())
    obs = {"rgb": jnp.ones((2, 3, 8, 8)), "state": jnp.ones((2, 5))}
    params = enc.init(KEY, obs)
    out = enc.apply(params, obs)
    assert out.shape == (2, 7)


def test_multi_encoder_requires_one():
    with pytest.raises(ValueError):
        MultiEncoder(cnn_encoder=None, mlp_encoder=None)


def test_multi_decoder_merge():
    import flax.linen as nn

    class CnnDec(nn.Module):
        @nn.compact
        def __call__(self, x):
            return {"rgb": jnp.ones((x.shape[0], 3, 8, 8))}

    class MlpDec(nn.Module):
        @nn.compact
        def __call__(self, x):
            return {"state": jnp.ones((x.shape[0], 5))}

    dec = MultiDecoder(cnn_decoder=CnnDec(), mlp_decoder=MlpDec())
    params = dec.init(KEY, jnp.ones((2, 16)))
    out = dec.apply(params, jnp.ones((2, 16)))
    assert set(out.keys()) == {"rgb", "state"}


def test_get_activation_accepts_torch_style_names():
    assert get_activation("torch.nn.SiLU") is get_activation("silu")
    assert get_activation("Tanh")(jnp.array(0.5)) == jnp.tanh(0.5)
    with pytest.raises(ValueError):
        get_activation("not_an_act")
