"""CLI-level tests: shim invocation, resume round-trip, resume mismatch errors,
evaluation from checkpoint (reference tests/test_algos/test_cli.py:99-277)."""

import os
import subprocess
import sys

import pytest

from sheeprl_tpu.cli import evaluation, run

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TINY_PPO = [
    "exp=ppo",
    "env=dummy",
    "env.id=discrete_dummy",
    "env.num_envs=1",
    "env.sync_env=True",
    "env.capture_video=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.rollout_steps=4",
    "algo.per_rank_batch_size=2",
    "algo.update_epochs=1",
    "algo.total_steps=16",
    "algo.mlp_keys.encoder=[state]",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.run_test=False",
    "buffer.memmap=False",
]


def _find_ckpts(root):
    found = []
    for base, _, files in os.walk(root):
        found += [os.path.join(base, f) for f in files if f.endswith(".ckpt")]
    return sorted(found)


def test_run_algo_subprocess(tmp_path):
    """The `python sheeprl.py ...` shim end-to-end in a fresh interpreter."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "sheeprl.py"), *TINY_PPO, "dry_run=True", "checkpoint.save_last=False"],
        cwd=tmp_path,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert out.returncode == 0, out.stderr[-2000:]


def test_resume_from_checkpoint(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(overrides=TINY_PPO + ["checkpoint.save_last=True"])
    ckpts = _find_ckpts(tmp_path / "logs")
    assert ckpts, "training did not write a checkpoint"
    run(overrides=TINY_PPO + ["checkpoint.save_last=False", f"checkpoint.resume_from={ckpts[-1]}"])


def test_resume_from_checkpoint_decoupled(tmp_path, monkeypatch):
    """Decoupled PPO writes its checkpoint from the player role with the
    trainer-world batch accounting; a resume must rebuild both roles from it
    (reference resumes decoupled runs through the same cli path)."""
    monkeypatch.chdir(tmp_path)
    tiny = [
        "exp=ppo_decoupled",
        "env=dummy",
        "env.id=discrete_dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.devices=3",
        "metric.log_level=0",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=2",
        "algo.update_epochs=1",
        "algo.total_steps=16",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.run_test=False",
        "buffer.memmap=False",
    ]
    # checkpoint MID-run (not save_last): the resume leg must actually train
    # from the restored state, not just load it and exit
    run(overrides=tiny + ["checkpoint.save_last=False", "checkpoint.every=8"])
    ckpts = _find_ckpts(tmp_path / "logs")
    assert ckpts, "decoupled training did not write a checkpoint"
    run(overrides=tiny + ["checkpoint.save_last=False", f"checkpoint.resume_from={ckpts[0]}"])


def test_resume_from_checkpoint_sac_decoupled(tmp_path, monkeypatch):
    """SAC decoupled checkpoints carry the replay-ratio scheduler and update
    counter alongside the params; resume must rehydrate all of it."""
    monkeypatch.chdir(tmp_path)
    tiny = [
        "exp=sac_decoupled",
        "env=dummy",
        "env.id=continuous_dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.devices=2",
        "metric.log_level=0",
        "algo.per_rank_batch_size=2",
        "algo.learning_starts=0",
        "algo.total_steps=8",
        "algo.hidden_size=8",
        "algo.mlp_keys.encoder=[state]",
        "algo.run_test=False",
        "buffer.memmap=False",
        "buffer.size=64",
    ]
    # checkpoint MID-run (not save_last) so the resume leg trains from the
    # restored scheduler/optimizer state instead of loading and exiting
    run(overrides=tiny + ["checkpoint.save_last=False", "checkpoint.every=4"])
    ckpts = _find_ckpts(tmp_path / "logs")
    assert ckpts, "decoupled SAC training did not write a checkpoint"
    run(overrides=tiny + ["checkpoint.save_last=False", f"checkpoint.resume_from={ckpts[0]}"])


def test_resume_from_checkpoint_env_error(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(overrides=TINY_PPO + ["checkpoint.save_last=True"])
    ckpts = _find_ckpts(tmp_path / "logs")
    args = [a if not a.startswith("env.id=") else "env.id=continuous_dummy" for a in TINY_PPO]
    with pytest.raises(ValueError, match="different environment"):
        run(overrides=args + [f"checkpoint.resume_from={ckpts[-1]}"])


def test_resume_from_checkpoint_algo_error(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(overrides=TINY_PPO + ["checkpoint.save_last=True"])
    ckpts = _find_ckpts(tmp_path / "logs")
    args = [a if a != "exp=ppo" else "exp=a2c" for a in TINY_PPO]
    with pytest.raises(ValueError, match="different algorithm"):
        run(overrides=args + [f"checkpoint.resume_from={ckpts[-1]}"])


def test_evaluate(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(overrides=TINY_PPO + ["checkpoint.save_last=True", "dry_run=True"])
    ckpts = _find_ckpts(tmp_path / "logs")
    assert ckpts
    evaluation(overrides=[f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


TINY_DV3 = [
    "exp=dreamer_v3",
    "env=dummy",
    "env.id=discrete_dummy",
    "env.num_envs=1",
    "env.sync_env=True",
    "env.capture_video=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.per_rank_batch_size=1",
    "algo.per_rank_sequence_length=2",
    "buffer.size=16",
    "algo.learning_starts=4",
    "algo.total_steps=8",
    "algo.replay_ratio=1",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=8",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "algo.run_test=False",
    "buffer.memmap=False",
]


def test_resume_and_evaluate_dreamer_v3(tmp_path, monkeypatch):
    """Checkpoint round-trip + eval-from-checkpoint for the flagship world model.

    The first run checkpoints MID-run (checkpoint.every=4 < total_steps=8), so
    the resume leg really trains iterations 5..8 with the restored optimizer /
    Moments / Ratio state (resume keeps the sidecar config's total_steps: CLI
    overrides other than checkpoint/seed/fabric are deliberately dropped on
    resume, reference cli.py:23-57)."""
    monkeypatch.chdir(tmp_path)
    run(overrides=TINY_DV3 + ["checkpoint.save_last=True", "checkpoint.every=4"])
    ckpts = _find_ckpts(tmp_path / "logs")
    assert ckpts, "DV3 training did not write a checkpoint"
    mid_ckpt = next(c for c in ckpts if "ckpt_4_" in os.path.basename(c))
    run(overrides=TINY_DV3 + ["checkpoint.save_last=False", f"checkpoint.resume_from={mid_ckpt}"])
    evaluation(overrides=[f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


def test_evaluate_requires_checkpoint_path():
    from sheeprl_tpu.config import ConfigError

    with pytest.raises(ConfigError, match="checkpoint_path"):
        evaluation(overrides=[])


def test_decoupled_requires_two_devices(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    with pytest.raises(RuntimeError, match="at least 2 devices"):
        run(
            overrides=[
                "exp=ppo_decoupled",
                "env=dummy",
                "env.id=discrete_dummy",
                "env.capture_video=False",
                "fabric.devices=1",
                "metric.log_level=0",
                "algo.mlp_keys.encoder=[state]",
                "dry_run=True",
            ]
        )


def test_cli_gates_backend_discovery_to_env_platforms(tmp_path):
    """JAX_PLATFORMS=cpu children must never initialize unrequested PJRT
    plugins: the env var selects a backend but does not gate eager plugin
    discovery, so a dead tunneled-TPU plugin hangs the process (round-5
    outage). cli.py applies the config-level jax_platforms gate; this pins
    the gate plus the resulting backend."""
    out = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sheeprl_tpu.cli, jax; "
            "assert jax.config.jax_platforms == 'cpu', jax.config.jax_platforms; "
            "print(jax.devices()[0].platform)",
        ],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.strip().endswith("cpu")
