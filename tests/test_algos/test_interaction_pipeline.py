"""The pipelined interaction loop must change scheduling, not semantics.

Three contracts from core/pipeline.py:

- ``PackedObsCodec.decode_obs`` is bit-identical to the per-key
  ``device_put`` + normalize path it replaced (cnn / mlp / mixed obs dicts).
- A steady-state pipelined PPO iteration performs EXACTLY the budgeted
  host<->device transfers: one packed obs put and one action fetch. The window
  between two consecutive ``step_async`` dispatches runs under
  ``jax.transfer_guard("disallow")`` (any implicit transfer raises) with the
  explicit entry points counted.
- Pipeline on vs off produces bit-identical trajectories over async env
  workers under a fixed seed: identical train-fn inputs and post-update params
  for PPO, identical replay-buffer rows for dreamer_v3.
"""

import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sheeprl_tpu.algos.dreamer_v3.dreamer_v3 as dv3_module
import sheeprl_tpu.algos.ppo.ppo as ppo_module
from sheeprl_tpu.cli import run
from sheeprl_tpu.core.pipeline import AsyncEnvStepper, PackedObsCodec
from sheeprl_tpu.data.prefetch import InlineSampler


def _args(standard_args, *extra):
    """standard_args with any key re-specified in ``extra`` dropped (hydra
    rejects duplicate value overrides)."""
    keys = {e.split("=", 1)[0].lstrip("+~") for e in extra}
    return [a for a in standard_args if a.split("=", 1)[0].lstrip("+~") not in keys] + list(extra)


# ----- PackedObsCodec: one-put path bit-identical to the per-key path -----------------


def _reference_decode(obs, cnn_keys, n_envs):
    """The pre-pipeline path: per-key device_put, normalize in a jitted fn."""

    def normalize(o):
        out = {}
        for k, v in o.items():
            leaf = v.astype(jnp.float32)
            if k in cnn_keys:
                out[k] = leaf.reshape(n_envs, -1, *v.shape[-2:]) / 255.0 - 0.5
            else:
                out[k] = leaf.reshape(n_envs, -1)
        return out

    return jax.jit(normalize)({k: jax.device_put(v) for k, v in obs.items()})


@pytest.mark.parametrize("case", ["cnn", "mlp", "mixed"])
def test_packed_codec_matches_per_key_path(case):
    n_envs = 3
    rng = np.random.default_rng(0)
    obs, cnn_keys = {}, []
    if case in ("cnn", "mixed"):
        obs["rgb"] = rng.integers(0, 256, (n_envs, 12, 8, 8), dtype=np.uint8)
        cnn_keys.append("rgb")
    if case in ("mlp", "mixed"):
        obs["state"] = rng.standard_normal((n_envs, 10)).astype(np.float32)

    codec = PackedObsCodec(cnn_keys=cnn_keys)
    decoded = jax.jit(codec.decode_obs)(codec.encode(obs))
    ref = _reference_decode(obs, cnn_keys, n_envs)

    assert set(decoded) == set(obs)
    for k in sorted(obs):
        np.testing.assert_array_equal(
            np.asarray(decoded[k]), np.asarray(ref[k]), err_msg=f"packed leaf '{k}' diverged"
        )


def test_packed_codec_extra_leaves_roundtrip():
    """Extras ride the obs transfer un-normalized, and survive the short
    extra-only flush buffer with the same layout."""
    obs = {"state": np.arange(6, dtype=np.float32).reshape(2, 3)}
    extra = {
        "rewards": np.asarray([[1.5], [-2.5]], np.float32),
        "dones": np.asarray([[0.0], [1.0]], np.float32),
    }
    codec = PackedObsCodec()
    packed = codec.encode(obs, extra=extra)
    dec = jax.jit(codec.decode_extra)(packed)
    for k in extra:
        np.testing.assert_array_equal(np.asarray(dec[k]), extra[k])

    flush = codec.encode_extra_only(extra)
    dec_flush = jax.jit(lambda p: codec.decode_extra(p, extra_only=True))(flush)
    for k in extra:
        np.testing.assert_array_equal(np.asarray(dec_flush[k]), extra[k])


# ----- transfer budget: one put + one fetch per steady-state pipelined step -----------

_PPO_ARGS = [
    "exp=ppo",
    "env=dummy",
    "env.id=discrete_dummy",
    "fabric.devices=1",
    "algo.rollout_steps=4",
    "algo.per_rank_batch_size=2",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.run_test=False",
    "buffer.memmap=False",
    "seed=7",
]


def test_ppo_pipelined_steady_state_transfer_budget(standard_args, tmp_path, monkeypatch):
    """Between step_async #3 and #4 (one full steady-state iteration: previous
    row close, step_wait, next encode + act + fetch) the loop may perform
    exactly ONE explicit jax.device_put (the packed obs) and ONE host pull of a
    jax array (the env actions); jax.transfer_guard makes anything implicit
    raise instead of silently widening the budget."""
    monkeypatch.chdir(tmp_path)
    counts = {"put": 0, "pull": 0, "dispatch": 0}
    active = [False]
    stack = contextlib.ExitStack()
    real_step_async = AsyncEnvStepper.step_async
    real_put = jax.device_put
    real_asarray = np.asarray

    def counting_put(x, *args, **kwargs):
        if active[0]:
            counts["put"] += 1
        return real_put(x, *args, **kwargs)

    def counting_asarray(obj, *args, **kwargs):
        if active[0] and isinstance(obj, jax.Array):
            counts["pull"] += 1
        return real_asarray(obj, *args, **kwargs)

    def windowed_step_async(self, actions):
        counts["dispatch"] += 1
        if counts["dispatch"] == 4 and active[0]:
            active[0] = False
            stack.close()
        real_step_async(self, actions)
        if counts["dispatch"] == 3:
            stack.enter_context(jax.transfer_guard("disallow"))
            active[0] = True

    try:
        with monkeypatch.context() as m:
            m.setattr(AsyncEnvStepper, "step_async", windowed_step_async)
            m.setattr(jax, "device_put", counting_put)
            m.setattr(np, "asarray", counting_asarray)
            run(
                overrides=_args(
                    standard_args, *_PPO_ARGS, "env.sync_env=False", "buffer.backend=device"
                )
            )
    finally:
        if active[0]:
            active[0] = False
            stack.close()

    assert counts["dispatch"] >= 4, "never reached the steady-state window"
    assert counts["put"] == 1, f"expected 1 packed obs put in the window, saw {counts['put']}"
    assert counts["pull"] == 1, f"expected 1 action fetch in the window, saw {counts['pull']}"


# ----- pipeline on/off parity: PPO train-fn inputs --------------------------------------


def _capture_ppo(standard_args, pipelined, monkeypatch):
    captured = []
    real_make_train_fn = ppo_module.make_train_fn

    def spy_make_train_fn(*args, **kwargs):
        train_fn = real_make_train_fn(*args, **kwargs)

        def wrapped(params, opt_state, data, next_values, key, clip_coef, ent_coef, *rest):
            out = train_fn(params, opt_state, data, next_values, key, clip_coef, ent_coef, *rest)
            captured.append(
                {
                    "data": {k: np.asarray(jax.device_get(v)) for k, v in data.items()},
                    "next_values": np.asarray(jax.device_get(next_values)),
                    "params": jax.device_get(out[0]),
                }
            )
            return out

        return wrapped

    with monkeypatch.context() as m:
        m.setattr(ppo_module, "make_train_fn", spy_make_train_fn)
        run(
            overrides=_args(
                standard_args,
                *_PPO_ARGS,
                "env.sync_env=False",
                f"algo.interaction_pipeline={pipelined}",
            )
        )
    assert len(captured) == 1, f"expected exactly one train call, got {len(captured)}"
    return captured[0]


def test_ppo_pipeline_on_off_parity(standard_args, tmp_path, monkeypatch):
    """Over async env workers under a fixed seed, flipping
    algo.interaction_pipeline must not change what reaches the train fn."""
    monkeypatch.chdir(tmp_path)
    on = _capture_ppo(standard_args, True, monkeypatch)
    off = _capture_ppo(standard_args, False, monkeypatch)

    assert set(on["data"]) == set(off["data"])
    for k in sorted(on["data"]):
        np.testing.assert_array_equal(
            on["data"][k], off["data"][k], err_msg=f"train-fn input '{k}' diverged across pipeline"
        )
    np.testing.assert_array_equal(on["next_values"], off["next_values"])

    on_leaves = jax.tree_util.tree_leaves_with_path(on["params"])
    off_leaves = dict(
        (jax.tree_util.keystr(p), l) for p, l in jax.tree_util.tree_leaves_with_path(off["params"])
    )
    assert on_leaves and len(on_leaves) == len(off_leaves)
    for path, leaf in on_leaves:
        np.testing.assert_array_equal(
            np.asarray(leaf),
            np.asarray(off_leaves[jax.tree_util.keystr(path)]),
            err_msg=f"post-update param {jax.tree_util.keystr(path)} diverged across pipeline",
        )


# ----- pipeline on/off parity: dreamer_v3 stored trajectories ---------------------------

_DV3_ARGS = [
    "exp=dreamer_v3",
    "env=dummy",
    "env.id=discrete_dummy",
    "fabric.devices=1",
    "algo.per_rank_batch_size=1",
    "algo.per_rank_sequence_length=1",
    "buffer.size=8",
    "algo.learning_starts=0",
    "algo.replay_ratio=1",
    "algo.horizon=8",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=8",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "buffer.memmap=False",
    "algo.run_test=False",
    "seed=11",
]


def _capture_dv3_rows(standard_args, pipelined, monkeypatch):
    """Run dreamer_v3 recording every rb.add row. The stock DevicePrefetcher
    speculates batches from a worker thread (racing the loop's adds) and the
    factory leaves the buffer rng unseeded, so batch content is nondeterministic
    run-to-run; determinism is restored by swapping in a synchronous
    InlineSampler and seeding the buffer — identically for both pipeline arms,
    so the comparison isolates the pipeline switch."""
    rows = []
    real_make_sequential_replay = dv3_module.make_sequential_replay

    def spy_make_sequential_replay(cfg, runtime, log_dir, obs_keys):
        rb, prefetcher = real_make_sequential_replay(cfg, runtime, log_dir, obs_keys)
        prefetcher.close()
        rb.seed(0)
        real_add = rb.add

        def recording_add(data, *args, **kwargs):
            idxes = args[0] if args else kwargs.get("indices")
            rows.append(
                (
                    {k: np.array(v, copy=True) for k, v in data.items()},
                    None if idxes is None else tuple(np.asarray(idxes).reshape(-1).tolist()),
                )
            )
            return real_add(data, *args, **kwargs)

        rb.add = recording_add
        return rb, InlineSampler(rb.sample)

    with monkeypatch.context() as m:
        m.setattr(dv3_module, "make_sequential_replay", spy_make_sequential_replay)
        run(
            overrides=_args(
                standard_args,
                *_DV3_ARGS,
                "env.sync_env=False",
                f"algo.interaction_pipeline={pipelined}",
            )
        )
    assert rows, "instrumentation never saw an rb.add"
    return rows


def test_dreamer_v3_pipeline_on_off_parity(standard_args, tmp_path, monkeypatch):
    """Same contract as the PPO test for the off-policy/sequential-replay shape:
    the rows dreamer_v3 writes to its replay buffer (content AND env indices)
    must be bit-identical across the pipeline switch."""
    monkeypatch.chdir(tmp_path)
    on = _capture_dv3_rows(standard_args, True, monkeypatch)
    off = _capture_dv3_rows(standard_args, False, monkeypatch)

    assert len(on) == len(off), f"row count diverged: {len(on)} vs {len(off)}"
    for i, ((row_on, idx_on), (row_off, idx_off)) in enumerate(zip(on, off)):
        assert idx_on == idx_off, f"add #{i} env indices diverged: {idx_on} vs {idx_off}"
        assert set(row_on) == set(row_off), f"add #{i} key set diverged"
        for k in sorted(row_on):
            np.testing.assert_array_equal(
                row_on[k], row_off[k], err_msg=f"add #{i} leaf '{k}' diverged across pipeline"
            )
