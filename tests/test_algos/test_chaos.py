"""End-to-end chaos suite: training survives injected env crashes, detects
injected NaNs per the configured policy, and a preempted run resumes
BIT-IDENTICALLY to an uninterrupted one (the ISSUE acceptance trio)."""

import os

import numpy as np
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.core.resilience import NonFiniteUpdateError, WorkerSupervisionError

CHAOS_WRAPPER = "env.wrapper._target_=sheeprl_tpu.envs.chaos.chaos_dummy_env"


def _tiny_ppo(total_steps=16, rollout_steps=4, num_envs=1):
    return [
        "exp=ppo",
        "env=dummy",
        "env.id=discrete_dummy",
        f"env.num_envs={num_envs}",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.devices=1",
        "metric.log_level=0",
        f"algo.rollout_steps={rollout_steps}",
        "algo.per_rank_batch_size=2",
        "algo.update_epochs=1",
        f"algo.total_steps={total_steps}",
        "algo.mlp_keys.encoder=[state]",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.run_test=False",
        "buffer.memmap=False",
        "checkpoint.every=0",
        "checkpoint.save_last=False",
    ]


def _find_ckpts(root):
    found = []
    for base, _, files in os.walk(root):
        found += [os.path.join(base, f) for f in files if f.endswith(".ckpt")]
    return sorted(found)


@pytest.mark.timeout(600)
def test_chaos_crash_worker_restarted_run_completes(tmp_path, monkeypatch):
    """crash_at=[3] crashes EVERY env incarnation at its 3rd step (the counter
    restarts with the rebuilt worker), so a 16-step run rides through ~5
    restarts — within the raised budget the run must simply complete."""
    monkeypatch.chdir(tmp_path)
    run(
        overrides=_tiny_ppo()
        + [
            CHAOS_WRAPPER,
            "env.wrapper.chaos.crash_at=[3]",
            "fault_tolerance.env_supervision.max_restarts=8",
            "fault_tolerance.env_supervision.backoff_base_s=0.0",
        ]
    )


@pytest.mark.timeout(600)
def test_chaos_crash_past_max_restarts_raises(tmp_path, monkeypatch):
    """An env that dies on EVERY incarnation's first step is a bug, not
    weather: the original fault must resurface once the budget is spent."""
    monkeypatch.chdir(tmp_path)
    with pytest.raises(WorkerSupervisionError, match="max_restarts"):
        run(
            overrides=_tiny_ppo()
            + [
                CHAOS_WRAPPER,
                "env.wrapper.chaos.crash_at=[1]",
                "fault_tolerance.env_supervision.max_restarts=1",
                "fault_tolerance.env_supervision.backoff_base_s=0.0",
            ]
        )


@pytest.mark.timeout(600)
def test_chaos_nan_halt_raises(tmp_path, monkeypatch):
    """An injected NaN reward flows through GAE into a non-finite loss; under
    policy=halt the exported skip counter (>0) must raise host-side — this is
    also the assertion that the in-graph guard actually FIRED."""
    monkeypatch.chdir(tmp_path)
    with pytest.raises(NonFiniteUpdateError, match="non-finite"):
        run(
            overrides=_tiny_ppo()
            + [
                CHAOS_WRAPPER,
                "env.wrapper.chaos.nan_at=[2]",
                "fault_tolerance.nonfinite.policy=halt",
            ]
        )


@pytest.mark.timeout(600)
def test_chaos_nan_skip_update_rides_through(tmp_path, monkeypatch):
    """Same injection, policy=skip_update: the poisoned update is dropped
    in-graph (params keep their previous finite values) and the run completes."""
    monkeypatch.chdir(tmp_path)
    run(
        overrides=_tiny_ppo()
        + [
            CHAOS_WRAPPER,
            "env.wrapper.chaos.nan_at=[2]",
            "fault_tolerance.nonfinite.policy=skip_update",
            "checkpoint.save_last=True",
        ]
    )
    ckpts = _find_ckpts(tmp_path / "logs")
    assert ckpts, "run did not finish and checkpoint"
    from sheeprl_tpu.utils.checkpoint import load_state

    import jax

    params = load_state(ckpts[-1])["agent"]
    for leaf in jax.tree_util.tree_leaves(params):
        assert np.isfinite(np.asarray(leaf)).all(), "NaN leaked into the params"


@pytest.mark.timeout(600)
def test_preemption_resume_bit_identical(tmp_path, monkeypatch):
    """The headline resilience property: SIGTERM'd-and-resumed == uninterrupted,
    leaf for leaf, for params AND optimizer state.

    Uses the deterministic stop_after_iters knob (same code path as the signal,
    minus delivery timing). rollout_steps=5 aligns iteration boundaries with
    the dummy env's 5-step episodes, so the env-side state is also identical
    across the resume (env state is deliberately not checkpointed)."""
    import jax

    from sheeprl_tpu.utils.checkpoint import load_state

    base = _tiny_ppo(total_steps=40, rollout_steps=5, num_envs=2)

    run_a = tmp_path / "runA"
    run_a.mkdir()
    monkeypatch.chdir(run_a)
    run(overrides=base + ["checkpoint.save_last=True"])
    ckpts_a = _find_ckpts(run_a / "logs")
    assert len(ckpts_a) == 1
    final_a = ckpts_a[0]

    run_b = tmp_path / "runB"
    run_b.mkdir()
    monkeypatch.chdir(run_b)
    run(overrides=base + ["fault_tolerance.preemption.stop_after_iters=2"])
    emergency = _find_ckpts(run_b / "logs")
    assert len(emergency) == 1, f"expected exactly the emergency checkpoint, got {emergency}"
    assert "ckpt_20_" in os.path.basename(emergency[0])  # mid-run, not the end

    run(
        overrides=base
        + ["checkpoint.save_last=True", f"checkpoint.resume_from={os.path.abspath(emergency[0])}"]
    )
    finals_b = [
        c
        for c in _find_ckpts(run_b / "logs")
        if os.path.basename(c) == os.path.basename(final_a)
    ]
    assert len(finals_b) == 1, "resumed run did not reach the same final step"

    state_a, state_b = load_state(final_a), load_state(finals_b[0])
    for key in ("agent", "optimizer"):
        leaves_a, treedef_a = jax.tree_util.tree_flatten(state_a[key])
        leaves_b, treedef_b = jax.tree_util.tree_flatten(state_b[key])
        assert treedef_a == treedef_b
        for la, lb in zip(leaves_a, leaves_b):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), (
                f"{key} diverged after preemption+resume"
            )
