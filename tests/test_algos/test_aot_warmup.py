"""AOT spec fidelity end-to-end: the eval_shape-derived warmup specs must match
the real first batch, so the hot-path entry points execute pre-built
executables (zero traces at call time) and record zero retraces over a short
multi-iteration run. This is the acceptance contract of the compile subsystem:
if a loop's spec derivation drifts from what it actually feeds the jitted
functions, these assertions are the first thing to break.
"""

import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.core import compile as jax_compile


def _assert_warmed(name: str):
    gfn = jax_compile.find(name)
    assert gfn is not None, f"{name} was never created"
    assert gfn.calls >= 1, f"{name} was never called"
    assert gfn.aot_compiles >= 1, f"{name} was not AOT-warmed"
    assert gfn.traces == 0, f"{name} traced {gfn.traces}x despite warmup (spec mismatch)"
    assert gfn.retraces == 0, f"{name} retraced: {gfn.last_diff}"
    return gfn


@pytest.mark.timeout(300)
def test_ppo_aot_specs_match_first_batch(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        overrides=[
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.devices=1",
            "algo.total_steps=48",  # 3 iterations of 2 envs x 8 steps
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.run_test=False",
            "metric.log_level=0",
            "checkpoint.save_last=False",
            "buffer.memmap=False",
        ]
    )
    train = _assert_warmed("ppo.train")
    assert train.calls == 3
    act = _assert_warmed("ppo.act_packed")
    assert act.calls >= 24  # one per env step


@pytest.mark.timeout(300)
def test_dreamer_v3_aot_specs_match_first_batch(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        overrides=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            "env.num_envs=1",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.devices=1",
            "algo.total_steps=8",  # 8 iterations (1 policy step each)
            "algo.learning_starts=2",
            "algo.replay_ratio=1",
            "algo.per_rank_batch_size=1",
            "algo.per_rank_sequence_length=1",
            "buffer.size=16",
            "algo.horizon=4",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=2",
            "algo.world_model.recurrent_model.recurrent_state_size=8",
            "algo.world_model.representation_model.hidden_size=8",
            "algo.world_model.transition_model.hidden_size=8",
            "algo.world_model.discrete_size=4",
            "algo.world_model.stochastic_size=4",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
            "algo.run_test=False",
            "metric.log_level=0",
            "checkpoint.save_last=False",
            "buffer.memmap=False",
        ]
    )
    train = _assert_warmed("dv3.train")
    assert train.calls >= 1
    # both the f32 post-reset state and the bf16 steady state must be covered
    step = _assert_warmed("dv3.step_packed")
    assert step.calls >= 2  # prefill iterations act randomly; the rest use the policy
