"""Acceptance gate for the health sentinel: with ``health.enabled=false`` the
loops must be bit-identical to a build without the subsystem, and with the
sentinel enabled-but-never-tripping the trained parameters must STILL be
bit-identical (the traced ``lr_scale`` operand is 1.0 and ``x * 1.0`` is exact
in IEEE arithmetic; the observe path is pure host-side bookkeeping)."""

import os

import numpy as np
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.utils.checkpoint import load_state


def _run_and_load(tmp_path, subdir, extra):
    root = tmp_path / subdir
    root.mkdir()
    args = [
        "dry_run=True",
        "exp=ppo",
        "env=dummy",
        "env.id=discrete_dummy",
        "env.num_envs=1",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.devices=1",
        "metric.log_level=0",
        "seed=7",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=2",
        "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "buffer.memmap=False",
        "checkpoint.save_last=True",
        f"root_dir={root}",
    ] + extra
    run(overrides=args)
    ckpts = []
    for r, _, files in os.walk(root):
        ckpts += [os.path.join(r, f) for f in files if f.endswith(".ckpt")]
    assert len(ckpts) == 1, ckpts
    return load_state(ckpts[0])


def _assert_tree_equal(a, b, path=""):
    if isinstance(a, dict):
        assert set(a) == set(b), path
        for k in a:
            _assert_tree_equal(a[k], b[k], f"{path}/{k}")
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_tree_equal(x, y, f"{path}[{i}]")
    elif isinstance(a, np.ndarray) or hasattr(a, "dtype"):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=path)
    # scalars/None/str in the state dict: exact match
    elif a is not None and not isinstance(a, float):
        assert a == b, path


@pytest.mark.timeout(300)
def test_ppo_bitwise_parity_health_off_vs_untripped(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    baseline = _run_and_load(tmp_path, "off", ["health.enabled=false"])
    # enabled with thresholds a 1-iteration dry run can never trip
    enabled = _run_and_load(
        tmp_path,
        "on",
        [
            "health.enabled=true",
            "health.divergence.warmup=64",
            "health.stall.warmup=64",
        ],
    )
    _assert_tree_equal(baseline["agent"], enabled["agent"], "agent")
    _assert_tree_equal(baseline["optimizer"], enabled["optimizer"], "optimizer")
    np.testing.assert_array_equal(np.asarray(baseline["rng"]), np.asarray(enabled["rng"]))
