"""End-to-end algorithm smoke tests through the real CLI.

Mirrors reference tests/test_algos/test_algos.py: every algorithm runs one iteration
(dry_run) on 2 sync dummy envs with tiny model dims; the `devices` parametrization
exercises the multi-device DP path on the virtual CPU mesh (conftest forces
--xla_force_host_platform_device_count=8, the analogue of the reference's LT_DEVICES
Gloo tests).
"""

import os

import pytest

from sheeprl_tpu.cli import run


@pytest.fixture(params=[1, 2])
def devices(request):
    return request.param


def _run(args):
    run(overrides=args)


@pytest.mark.parametrize("env_id", ["discrete_dummy", "multidiscrete_dummy", "continuous_dummy"])
def test_ppo(standard_args, env_id, devices, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=ppo",
        "env=dummy",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=2",
        "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "buffer.memmap=False",
        "env.num_envs=1",
    ]
    _run(args)


def test_ppo_vector_only(standard_args, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=ppo",
        "env=dummy",
        "env.id=discrete_dummy",
        "fabric.devices=1",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=2",
        "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "buffer.memmap=False",
        "env.num_envs=2",
    ]
    _run(args)


def test_ppo_checkpoint_written(standard_args, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=ppo",
        "env=dummy",
        "env.id=discrete_dummy",
        "fabric.devices=1",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=2",
        "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "buffer.memmap=False",
        "env.num_envs=1",
        "checkpoint.save_last=True",
    ]
    _run(args)
    ckpts = []
    for root, _, files in os.walk(tmp_path / "logs"):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    assert len(ckpts) >= 1


@pytest.mark.parametrize("env_id,devices", [("discrete_dummy", 1), ("continuous_dummy", 1), ("discrete_dummy", 2)])
def test_a2c(standard_args, env_id, devices, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=a2c",
        "env=dummy",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=2",
        "algo.mlp_keys.encoder=[state]",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "buffer.memmap=False",
        "env.num_envs=2",
    ]
    _run(args)


@pytest.mark.parametrize("devices", [1, 2])
def test_sac(standard_args, devices, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=sac",
        "env=dummy",
        "env.id=continuous_dummy",
        f"fabric.devices={devices}",
        "algo.per_rank_batch_size=2",
        "algo.learning_starts=0",
        "algo.hidden_size=8",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
        "buffer.size=64",
        "env.num_envs=2",
    ]
    _run(args)


def test_sac_player_sync_every(standard_args, tmp_path, monkeypatch):
    """Deferred trainer->player refreshes (remote-accelerator amortization) train
    end-to-end, including the forced final sync before evaluation."""
    monkeypatch.chdir(tmp_path)
    args = [a for a in standard_args if a != "dry_run=True"] + [
        "exp=sac",
        "env=dummy",
        "env.id=continuous_dummy",
        "fabric.devices=1",
        "algo.per_rank_batch_size=2",
        "algo.learning_starts=0",
        "algo.hidden_size=8",
        "algo.mlp_keys.encoder=[state]",
        "algo.player_sync_every=3",
        "algo.total_steps=16",
        "algo.run_test=True",
        "buffer.memmap=False",
        "buffer.size=64",
        "env.num_envs=2",
    ]
    _run(args)


def test_sac_rejects_discrete(standard_args, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=sac",
        "env=dummy",
        "env.id=discrete_dummy",
        "fabric.devices=1",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
        "env.num_envs=1",
    ]
    with pytest.raises(ValueError, match="continuous action space"):
        _run(args)


@pytest.mark.parametrize("devices", [1, 2])
def test_droq(standard_args, devices, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=droq",
        "env=dummy",
        "env.id=continuous_dummy",
        f"fabric.devices={devices}",
        "algo.per_rank_batch_size=2",
        "algo.learning_starts=0",
        "algo.hidden_size=8",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
        "buffer.size=64",
        "env.num_envs=2",
    ]
    _run(args)


@pytest.mark.parametrize("devices", [1, 2])
def test_sac_ae(standard_args, devices, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=sac_ae",
        "env=dummy",
        "env.id=continuous_dummy",
        f"fabric.devices={devices}",
        "algo.per_rank_batch_size=2",
        "algo.learning_starts=0",
        "algo.hidden_size=8",
        "algo.dense_units=8",
        "algo.cnn_channels_multiplier=1",
        "algo.encoder.features_dim=8",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        "buffer.memmap=False",
        "buffer.size=64",
        "env.num_envs=1",
        "env.screen_size=64",
        "env.frame_stack=1",
    ]
    _run(args)


@pytest.mark.parametrize("env_id,devices", [("discrete_dummy", 1), ("continuous_dummy", 1), ("discrete_dummy", 2)])
def test_ppo_recurrent(standard_args, env_id, devices, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=ppo_recurrent",
        "env=dummy",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        "algo.rollout_steps=8",
        "algo.per_rank_sequence_length=4",
        "algo.per_rank_num_batches=2",
        "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.rnn.lstm.hidden_size=8",
        "buffer.memmap=False",
        "env.num_envs=2",
    ]
    _run(args)


@pytest.mark.parametrize("env_id", ["discrete_dummy", "continuous_dummy"])
def test_dreamer_v1(standard_args, env_id, devices, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=dreamer_v1",
        "env=dummy",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        "algo.per_rank_pretrain_steps=1",
        "algo.per_rank_batch_size=1",
        "algo.per_rank_sequence_length=1",
        "buffer.size=16",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.horizon=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.stochastic_size=4",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
        "env.num_envs=1",
    ]
    _run(args)


@pytest.mark.parametrize("env_id", ["discrete_dummy", "continuous_dummy"])
def test_dreamer_v2(standard_args, env_id, devices, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=dreamer_v2",
        "env=dummy",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        "algo.per_rank_pretrain_steps=1",
        "algo.per_rank_batch_size=1",
        "algo.per_rank_sequence_length=1",
        "buffer.size=16",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.horizon=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
        "env.num_envs=1",
    ]
    _run(args)


def test_dreamer_v2_episode_buffer_continues(standard_args, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=dreamer_v2",
        "env=dummy",
        "env.id=discrete_dummy",
        "fabric.devices=1",
        "algo.per_rank_pretrain_steps=1",
        "algo.per_rank_batch_size=1",
        "algo.per_rank_sequence_length=1",
        "buffer.size=16",
        "buffer.type=episode",
        "algo.world_model.use_continues=True",
        "algo.actor.expl_amount=0.3",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.horizon=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
        "env.num_envs=1",
    ]
    _run(args)


@pytest.mark.parametrize("env_id", ["discrete_dummy", "continuous_dummy"])
def test_dreamer_v3(standard_args, env_id, devices, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=dreamer_v3",
        "env=dummy",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        "algo.per_rank_batch_size=1",
        "algo.per_rank_sequence_length=1",
        "buffer.size=4",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.horizon=8",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
        "env.num_envs=1",
    ]
    _run(args)


def test_dreamer_v3_decoupled_rssm(standard_args, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=dreamer_v3",
        "env=dummy",
        "env.id=discrete_dummy",
        "fabric.devices=1",
        "algo.per_rank_batch_size=1",
        "algo.per_rank_sequence_length=1",
        "buffer.size=4",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.horizon=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.decoupled_rssm=True",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
        "env.num_envs=1",
    ]
    _run(args)


_P2E_DV1_TINY = [
    "env=dummy",
    "algo.per_rank_pretrain_steps=1",
    "algo.per_rank_batch_size=1",
    "algo.per_rank_sequence_length=2",
    "buffer.size=16",
    "algo.learning_starts=0",
    "algo.replay_ratio=1",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.ensembles.n=2",
    "algo.ensembles.dense_units=8",
    "algo.ensembles.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=8",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.stochastic_size=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "buffer.memmap=False",
    "env.num_envs=1",
]


@pytest.mark.parametrize("env_id,devices", [("discrete_dummy", 1), ("continuous_dummy", 1), ("discrete_dummy", 2)])
def test_p2e_dv1(standard_args, env_id, devices, tmp_path, monkeypatch):
    """Exploration phase then finetuning from its checkpoint (reference
    tests/test_algos/test_algos.py p2e flow)."""
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=p2e_dv1_exploration",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        "checkpoint.save_last=True",
    ] + _P2E_DV1_TINY
    _run(args)

    ckpts = []
    for root, _, files in os.walk(tmp_path / "logs"):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    assert len(ckpts) >= 1

    args = standard_args + [
        "exp=p2e_dv1_finetuning",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        f"checkpoint.exploration_ckpt_path={ckpts[0]}",
    ] + _P2E_DV1_TINY
    _run(args)


_P2E_DV2_TINY = _P2E_DV1_TINY + [
    "algo.world_model.discrete_size=4",
    "algo.critic.per_rank_target_network_update_freq=2",
]


@pytest.mark.parametrize("env_id,devices", [("discrete_dummy", 1), ("continuous_dummy", 1), ("discrete_dummy", 2)])
def test_p2e_dv2(standard_args, env_id, devices, tmp_path, monkeypatch):
    """Exploration phase then finetuning from its checkpoint (reference
    tests/test_algos/test_algos.py p2e flow)."""
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=p2e_dv2_exploration",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        "checkpoint.save_last=True",
    ] + _P2E_DV2_TINY
    _run(args)

    ckpts = []
    for root, _, files in os.walk(tmp_path / "logs"):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    assert len(ckpts) >= 1

    args = standard_args + [
        "exp=p2e_dv2_finetuning",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        f"checkpoint.exploration_ckpt_path={ckpts[0]}",
    ] + _P2E_DV2_TINY
    _run(args)


_P2E_DV3_TINY = _P2E_DV2_TINY + [
    # DV3-style mains add one row per iteration (no initial reset add), so a dry
    # run only has 1 sample (reference tests/test_algos/test_algos.py:497)
    "algo.per_rank_sequence_length=1",
    "algo.world_model.reward_model.bins=5",
    "algo.critic.bins=5",
]


@pytest.mark.parametrize("env_id,devices", [("discrete_dummy", 1), ("continuous_dummy", 1), ("discrete_dummy", 2)])
def test_p2e_dv3(standard_args, env_id, devices, tmp_path, monkeypatch):
    """Exploration phase then finetuning from its checkpoint (reference
    tests/test_algos/test_algos.py p2e flow)."""
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=p2e_dv3_exploration",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        "checkpoint.save_last=True",
    ] + _P2E_DV3_TINY
    _run(args)

    ckpts = []
    for root, _, files in os.walk(tmp_path / "logs"):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    assert len(ckpts) >= 1

    # The exploration run must not have produced NaNs anywhere (guards the
    # degenerate T=1 ensemble slice and any future NaN poisoning).
    import jax
    import numpy as np

    from sheeprl_tpu.utils.checkpoint import load_state

    expl_state = load_state(ckpts[0])
    for name in ("world_model", "ensembles", "actor_exploration", "critics_exploration", "actor_task"):
        for leaf in jax.tree_util.tree_leaves(expl_state[name]):
            assert np.isfinite(np.asarray(leaf)).all(), f"non-finite values in checkpointed '{name}'"

    args = standard_args + [
        "exp=p2e_dv3_finetuning",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        f"checkpoint.exploration_ckpt_path={ckpts[0]}",
    ] + _P2E_DV3_TINY
    _run(args)


@pytest.mark.parametrize("env_id,devices", [("discrete_dummy", 1), ("continuous_dummy", 1), ("discrete_dummy", 2)])
def test_dream_and_ponder(standard_args, env_id, devices, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=dream_and_ponder",
        "env=dummy",
        f"env.id={env_id}",
        f"fabric.devices={devices}",
        "algo.per_rank_batch_size=1",
        "algo.per_rank_sequence_length=1",
        "buffer.size=4",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.horizon=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.ponder.max_ponder_steps=2",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "algo.world_model.reward_model.bins=5",
        "algo.critic.bins=5",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
        "env.num_envs=1",
    ]
    _run(args)


@pytest.mark.parametrize("env_id", ["discrete_dummy", "continuous_dummy"])
def test_ppo_decoupled(standard_args, env_id, tmp_path, monkeypatch):
    """Player on device 0, trainers on the rest of the CPU mesh (reference
    tests run the decoupled algos with LT_DEVICES=2 over Gloo)."""
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=ppo_decoupled",
        "env=dummy",
        f"env.id={env_id}",
        "fabric.devices=3",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=2",
        "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "buffer.memmap=False",
        "env.num_envs=2",
        "checkpoint.save_last=True",
    ]
    _run(args)
    ckpts = []
    for root, _, files in os.walk(tmp_path / "logs"):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    assert len(ckpts) >= 1


@pytest.mark.mesh
def test_ppo_decoupled_fsdp(standard_args, tmp_path, monkeypatch):
    """Decoupled PPO under ``fabric.strategy=fsdp``: the player stays on its
    own device while the trainer sub-mesh shards params/opt-state, the
    rollout handoff arrives one put per trainer shard (its failpoint seam is
    armed in benign fire mode and must trip), and the params flow back
    through the all-gathering player sync."""
    from sheeprl_tpu.core import failpoints

    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=ppo_decoupled",
        "env=dummy",
        "env.id=discrete_dummy",
        "fabric.devices=3",
        "fabric.strategy=fsdp",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=2",
        "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "buffer.memmap=False",
        "env.num_envs=2",
    ]
    with failpoints.active("handoff.shard_put:fire"):
        _run(args)
        fires = failpoints.counts()["handoff.shard_put"]["fires"]
    assert fires >= 1, "the trainer never passed through the per-shard handoff seam"


def test_ppo_decoupled_rejects_single_device(standard_args, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=ppo_decoupled",
        "env=dummy",
        "env.id=discrete_dummy",
        "fabric.devices=1",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
    ]
    with pytest.raises(RuntimeError, match="requires at least 2 devices"):
        _run(args)


def test_sac_decoupled(standard_args, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=sac_decoupled",
        "env=dummy",
        "env.id=continuous_dummy",
        "fabric.devices=2",
        "algo.per_rank_batch_size=2",
        "algo.learning_starts=0",
        "algo.hidden_size=8",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
        "buffer.size=64",
        "env.num_envs=2",
    ]
    _run(args)


@pytest.mark.mesh
def test_sac_decoupled_fsdp(standard_args, tmp_path, monkeypatch):
    """Decoupled SAC under ``fabric.strategy=fsdp``: replay batches reach the
    sharded trainer sub-mesh through the per-shard handoff."""
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=sac_decoupled",
        "env=dummy",
        "env.id=continuous_dummy",
        "fabric.devices=3",
        "fabric.strategy=fsdp",
        "algo.per_rank_batch_size=2",
        "algo.learning_starts=0",
        "algo.hidden_size=8",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
        "buffer.size=64",
        "env.num_envs=2",
    ]
    _run(args)


def test_sac_decoupled_rejects_single_device(standard_args, tmp_path, monkeypatch):
    """Reference parity: decoupled SAC must refuse to run on one device
    (reference tests/test_algos/test_algos.py test_sac_decoupled)."""
    monkeypatch.chdir(tmp_path)
    args = standard_args + [
        "exp=sac_decoupled",
        "env=dummy",
        "env.id=continuous_dummy",
        "fabric.devices=1",
        "algo.mlp_keys.encoder=[state]",
        "buffer.memmap=False",
    ]
    with pytest.raises(RuntimeError, match="requires at least 2 devices"):
        _run(args)
