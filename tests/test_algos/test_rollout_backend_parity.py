"""buffer.backend=host vs device must be a pure transport change for PPO.

Two end-to-end CLI runs under a fixed seed must feed the jitted train fn
bit-identical ``[T, B]`` rollouts and produce bit-identical post-update params;
and the device-backend hot loop must never pull ``values``/``logprobs`` to host
per step (the instrumentation poisons ``__array__`` on exactly those arrays).
"""

import jax
import numpy as np
import pytest

import sheeprl_tpu.algos.ppo.ppo as ppo_module
from sheeprl_tpu.algos.ppo.agent import PPOPlayer
from sheeprl_tpu.cli import run

_PPO_ARGS = [
    "exp=ppo",
    "env=dummy",
    "env.id=discrete_dummy",
    "fabric.devices=1",
    "algo.rollout_steps=4",
    "algo.per_rank_batch_size=2",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.run_test=False",
    "buffer.memmap=False",
    "seed=7",
]


def _run_and_capture(standard_args, backend, monkeypatch):
    """Run one dry-run PPO iteration; capture the train fn's exact inputs and
    the post-update params via a make_train_fn spy."""
    captured = []
    real_make_train_fn = ppo_module.make_train_fn

    def spy_make_train_fn(*args, **kwargs):
        train_fn = real_make_train_fn(*args, **kwargs)

        def wrapped(params, opt_state, data, next_values, key, clip_coef, ent_coef, *rest):
            out = train_fn(params, opt_state, data, next_values, key, clip_coef, ent_coef, *rest)
            captured.append(
                {
                    "data": {k: np.asarray(jax.device_get(v)) for k, v in data.items()},
                    "next_values": np.asarray(jax.device_get(next_values)),
                    "params": jax.device_get(out[0]),
                }
            )
            return out

        return wrapped

    with monkeypatch.context() as m:
        m.setattr(ppo_module, "make_train_fn", spy_make_train_fn)
        run(overrides=standard_args + _PPO_ARGS + [f"buffer.backend={backend}"])
    assert len(captured) == 1, f"expected exactly one train call, got {len(captured)}"
    return captured[0]


def test_ppo_backend_parity(standard_args, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    host = _run_and_capture(standard_args, "host", monkeypatch)
    device = _run_and_capture(standard_args, "device", monkeypatch)

    assert set(host["data"]) == set(device["data"])
    for k in sorted(host["data"]):
        np.testing.assert_array_equal(
            host["data"][k], device["data"][k], err_msg=f"train-fn input '{k}' diverged across backends"
        )
    np.testing.assert_array_equal(host["next_values"], device["next_values"])

    host_leaves = jax.tree_util.tree_leaves_with_path(host["params"])
    dev_leaves = dict(
        (jax.tree_util.keystr(p), l) for p, l in jax.tree_util.tree_leaves_with_path(device["params"])
    )
    assert host_leaves and len(host_leaves) == len(dev_leaves)
    for path, leaf in host_leaves:
        np.testing.assert_array_equal(
            np.asarray(leaf),
            np.asarray(dev_leaves[jax.tree_util.keystr(path)]),
            err_msg=f"post-update param {jax.tree_util.keystr(path)} diverged across backends",
        )


def _poison_policy_outputs(monkeypatch_ctx):
    """Intercept every act_raw call and poison its values/logprobs outputs:
    any host materialization of them (np.asarray / np.array / jax.device_get)
    raises. Returns the forbidden-id registry (also the proof act_raw ran).

    np.asarray on a jax CPU array does NOT go through the Python-level
    ``ArrayImpl.__array__`` (numpy hits the array-interface/buffer protocol
    first), so the guard wraps the numpy entry points themselves.
    """
    forbidden = {}  # id -> strong ref (keeps ids stable for the run's lifetime)
    real_act_raw = PPOPlayer.act_raw
    real_act_packed = PPOPlayer.act_packed

    def spy_act_raw(self, obs, key, **kwargs):
        out = real_act_raw(self, obs, key, **kwargs)
        forbidden[id(out[2])] = out[2]  # logprobs
        forbidden[id(out[3])] = out[3]  # values
        return out

    def spy_act_packed(self, codec, packed, key, **kwargs):
        out = real_act_packed(self, codec, packed, key, **kwargs)
        forbidden[id(out[2])] = out[2]  # logprobs
        forbidden[id(out[3])] = out[3]  # values
        return out

    def make_guard(real):
        def guarded(obj, *args, **kwargs):
            if id(obj) in forbidden:
                raise AssertionError(
                    "per-step host pull of values/logprobs from the PPO hot loop"
                )
            return real(obj, *args, **kwargs)

        return guarded

    monkeypatch_ctx.setattr(PPOPlayer, "act_raw", spy_act_raw)
    monkeypatch_ctx.setattr(PPOPlayer, "act_packed", spy_act_packed)
    monkeypatch_ctx.setattr(np, "asarray", make_guard(np.asarray))
    monkeypatch_ctx.setattr(np, "array", make_guard(np.array))
    monkeypatch_ctx.setattr(jax, "device_get", make_guard(jax.device_get))
    return forbidden


def test_ppo_device_backend_never_pulls_policy_outputs(standard_args, tmp_path, monkeypatch):
    """The device-backend hot loop's only device->host sync is the env actions:
    values/logprobs must reach the train fn without ever touching host."""
    monkeypatch.chdir(tmp_path)
    with monkeypatch.context() as m:
        forbidden = _poison_policy_outputs(m)
        run(overrides=standard_args + _PPO_ARGS + ["buffer.backend=device"])
    assert forbidden, "instrumentation never saw an act_raw call"


def test_ppo_host_backend_does_pull_policy_outputs(standard_args, tmp_path, monkeypatch):
    """Sanity check on the instrumentation itself: the host-backend reference
    loop MUST trip the same poison (np.asarray per step), proving the
    device-backend test above would catch a regression."""
    monkeypatch.chdir(tmp_path)
    with monkeypatch.context() as m:
        forbidden = _poison_policy_outputs(m)
        with pytest.raises(AssertionError, match="host pull"):
            run(overrides=standard_args + _PPO_ARGS + ["buffer.backend=host"])
    assert forbidden
