"""Print the (normalized) Dict observation space an agent will see for any env
config — useful before picking ``algo.cnn_keys``/``algo.mlp_keys``.

Reference counterpart: examples/observation_space.py.

Usage:
    python examples/observation_space.py env=gym env.id=CartPole-v1 algo=ppo \
        algo.mlp_keys.encoder=[state]
    python examples/observation_space.py env=dummy env.id=discrete_dummy algo=dreamer_v3
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheeprl_tpu.config import compose
from sheeprl_tpu.utils.env import make_env


def main() -> None:
    overrides = sys.argv[1:]
    # an exp recipe is not required for inspecting spaces: default to ppo
    if not any(o.startswith("exp=") for o in overrides):
        overrides = ["exp=ppo", *overrides]
    cfg = compose(overrides=overrides)
    cfg.env.capture_video = False
    env = make_env(cfg, cfg.seed, 0, None, "space-check")()
    try:
        print("Observation space:")
        for key, space in env.observation_space.spaces.items():
            print(f"  {key}: {space}")
        print("Action space:", env.action_space)
        print()
        print("Encoder keys selected by this config:")
        print("  cnn:", list(cfg.algo.cnn_keys.encoder))
        print("  mlp:", list(cfg.algo.mlp_keys.encoder))
    finally:
        env.close()


if __name__ == "__main__":
    main()
