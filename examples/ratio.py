"""Demonstrate the ``Ratio`` replay scheduler: how many gradient steps a given
``algo.replay_ratio`` yields as policy steps accumulate.

Reference counterpart: examples/ratio.py.

Usage:
    python examples/ratio.py 0.5 1024 64
    # replay_ratio=0.5, 1024 total policy steps, 64 policy steps per iteration
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheeprl_tpu.utils.utils import Ratio


def main() -> None:
    replay_ratio = float(sys.argv[1]) if len(sys.argv) > 1 else 0.5
    total_steps = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    per_iter = int(sys.argv[3]) if len(sys.argv) > 3 else 64

    ratio = Ratio(replay_ratio)
    total_grad_steps = 0
    for policy_step in range(per_iter, total_steps + 1, per_iter):
        g = ratio(policy_step)
        total_grad_steps += g
        print(f"policy_step={policy_step:6d} -> {g:3d} gradient steps (cumulative {total_grad_steps})")
    print(
        f"\nrealized replay ratio: {total_grad_steps / total_steps:.4f} "
        f"(requested {replay_ratio})"
    )


if __name__ == "__main__":
    main()
