"""Roll the DreamerV3 world model forward in imagination from a checkpoint and
dump reconstructed frames — the script form of the reference's
notebooks/dreamer_v3_imagination.ipynb.

Usage:
    python examples/dreamer_v3_imagination.py \
        checkpoint_path=logs/runs/dreamer_v3/.../ckpt_1024_0.ckpt [horizon=32] [out=imagination.npz]

Starting from a real observation, the script encodes it, steps the RSSM with
the trained actor's actions for ``horizon`` imagined steps, decodes every
latent back to pixels, and saves ``[horizon, C, H, W]`` reconstructions plus
the imagined rewards/continues to an ``.npz``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import yaml

from sheeprl_tpu.algos.dreamer_v3.agent import ActorOutput, build_agent
from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs
from sheeprl_tpu.core.runtime import Runtime
from sheeprl_tpu.ops.distributions import BernoulliSafeMode, Independent, TwoHotEncodingDistribution
from sheeprl_tpu.utils.checkpoint import load_state
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.utils import dotdict


def main() -> None:
    kv = dict(a.split("=", 1) for a in sys.argv[1:])
    ckpt_path = os.path.abspath(kv["checkpoint_path"])
    horizon = int(kv.get("horizon", 32))
    out_path = kv.get("out", "imagination.npz")

    with open(os.path.join(os.path.dirname(ckpt_path), os.pardir, "config.yaml")) as f:
        cfg = dotdict(yaml.safe_load(f))
    cfg.env.num_envs = 1
    cfg.env.capture_video = False

    runtime = Runtime(accelerator=cfg.fabric.get("accelerator", "auto"), devices=1, precision=cfg.fabric.precision)
    state = load_state(ckpt_path)

    env = make_env(cfg, cfg.seed, 0, None, "imagination")()
    action_space = env.action_space
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape if is_continuous else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    obs_space = gym.spaces.Dict({k: env.observation_space[k] for k in env.observation_space.spaces})
    modules, params, player = build_agent(
        runtime, actions_dim, is_continuous, cfg, obs_space,
        state["world_model"], state["actor"], state["critic"], state["target_critic"],
    )
    wm, actor_params = params["world_model"], params["actor"]
    rssm = modules.rssm

    # ---- encode one real observation into the posterior
    obs = env.reset(seed=cfg.seed)[0]
    jax_obs = prepare_obs(runtime, obs, cnn_keys=cfg.algo.cnn_keys.encoder, num_envs=1)
    embedded = modules.encoder.apply(wm["encoder"], {k: v[0] for k, v in jax_obs.items()})
    key = jax.random.PRNGKey(cfg.seed)
    rec, stoch = rssm.initial_states(wm, (1,))
    post_logits, post = rssm._representation(wm, embedded, key, recurrent_state=rec)
    prior_flat = post.reshape(1, -1)

    # ---- imagine forward with the trained policy
    frames, rewards, continues = [], [], []
    cnn_keys_dec = list(cfg.algo.cnn_keys.decoder)
    if not cnn_keys_dec:
        raise SystemExit(
            "This checkpoint was trained without pixel observations "
            "(algo.cnn_keys.decoder is empty) — there are no frames to imagine."
        )
    cnn_key = cnn_keys_dec[0]
    for t in range(horizon):
        key, k_act, k_img = jax.random.split(key, 3)
        latent = jnp.concatenate([prior_flat, rec], axis=-1)
        out = ActorOutput(modules.actor, modules.actor.apply(actor_params, latent))
        action = jnp.concatenate(out.sample_actions(k_act), axis=-1)
        prior_flat, rec = rssm.imagination_step(wm, prior_flat, rec, action, k_img)
        latent = jnp.concatenate([prior_flat, rec], axis=-1)
        recon = modules.observation_model.apply(wm["observation_model"], latent)
        frames.append(np.asarray(jnp.clip((recon[cnn_key][0] + 0.5) * 255.0, 0, 255)).astype(np.uint8))
        rewards.append(
            float(TwoHotEncodingDistribution(modules.reward_model.apply(wm["reward_model"], latent), dims=1).mean[0, 0])
        )
        continues.append(
            float(Independent(BernoulliSafeMode(logits=modules.continue_model.apply(wm["continue_model"], latent)), 1).base.mode[0, 0])
        )

    np.savez(out_path, frames=np.stack(frames), rewards=np.array(rewards), continues=np.array(continues))
    print(f"imagined {horizon} steps -> {out_path}; mean imagined reward {np.mean(rewards):.3f}")
    env.close()


if __name__ == "__main__":
    main()
