"""End-to-end model-manager walkthrough (reference examples/model_manager.ipynb).

The reference notebook trains a short PPO run against an MLflow server, then
drives MlflowModelManager through register -> get latest -> transition ->
register-best -> download -> delete. This script is the same tour on the
TPU build's default backend, the filesystem ``LocalModelManager``
(sheeprl_tpu/utils/model_manager.py) — no server required; point
``model_manager.registry_dir`` at shared storage to share a registry.

Run from the repo root (a minute on CPU)::

    JAX_PLATFORMS=cpu python examples/model_manager.py

Every step prints what it did; the registry lands in a temp dir by default
(override with --registry-dir to keep it).
"""

from __future__ import annotations

import argparse
import glob
import os
import pickle
import tempfile


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--registry-dir", default=None, help="keep the registry here instead of a temp dir")
    args = parser.parse_args()

    registry_dir = args.registry_dir or os.path.join(tempfile.mkdtemp(prefix="sheeprl_tpu_registry_"), "registry")

    # ---- 1. train a short PPO run on CartPole (the notebook's first cell: a small
    # experiment whose checkpoint feeds the registry; quality is not the point)
    from sheeprl_tpu.cli import run

    run(
        overrides=[
            "exp=ppo",
            "algo.total_steps=2048",
            "algo.rollout_steps=128",
            "env.num_envs=4",
            "env.sync_env=True",
            "env.capture_video=False",
            "algo.mlp_keys.encoder=[state]",
            "checkpoint.every=2048",
            "metric.log_level=1",
            "metric.disable_timer=True",
            "exp_name=model_manager_example",
        ]
    )
    run_dirs = sorted(glob.glob("logs/runs/ppo/CartPole-v1/*model_manager_example*/version_*"), key=os.path.getmtime)
    assert run_dirs, "the PPO run should have produced a versioned log dir"
    run_dir = run_dirs[-1]
    ckpts = sorted(glob.glob(os.path.join(run_dir, "checkpoint", "*.ckpt")), key=os.path.getmtime)
    assert ckpts, f"no checkpoint under {run_dir}"
    print(f"\n[1] trained PPO; checkpoint: {ckpts[-1]}")

    # ---- 2. register the agent from the checkpoint (notebook: register_model)
    from sheeprl_tpu.utils.checkpoint import load_state
    from sheeprl_tpu.utils.model_manager import LocalModelManager

    manager = LocalModelManager(None, registry_dir)
    state = load_state(ckpts[-1])
    with tempfile.TemporaryDirectory() as tmp:
        agent_path = os.path.join(tmp, "agent.pkl")
        with open(agent_path, "wb") as f:
            pickle.dump(state["agent"], f, protocol=pickle.HIGHEST_PROTOCOL)
        mv = manager.register_model(
            agent_path,
            "ppo_cartpole_agent",
            description="PPO agent from the model-manager example",
            tags={"algo": "ppo", "env": "CartPole-v1"},
        )
    print(f"[2] registered '{mv.name}' v{mv.version} at {mv.path}")

    # ---- 3. retrieve the latest version (notebook: get_latest_version)
    latest = manager.get_latest_version("ppo_cartpole_agent")
    print(f"[3] latest version: v{latest.version} (stage={latest.stage!r}, description={latest.description!r})")

    # ---- 4. transition it to a stage (notebook: transition_model to 'staging')
    staged = manager.transition_model(
        "ppo_cartpole_agent", latest.version, "staging", description="promoted by examples/model_manager.py"
    )
    print(f"[4] transitioned v{staged.version} -> stage {staged.stage!r}")

    # ---- 5. register the best run under the experiment dir (the RL-flavored
    # flow the notebook closes with: rank runs by a test metric, register the winner)
    try:
        best = manager.register_best_models(
            os.path.dirname(run_dir), {"agent"}, metric="Test/cumulative_reward"
        )
        for name, version in best.items():
            print(f"[5] best-run registration: '{name}' -> v{version.version} ({version.description})")
    except RuntimeError as e:
        # run_test=False or a metrics-less run leaves nothing to rank — not an error here
        print(f"[5] best-run registration skipped: {e}")

    # ---- 6. download an artifact copy (notebook: download_model)
    with tempfile.TemporaryDirectory() as out:
        manager.download_model("ppo_cartpole_agent", latest.version, out)
        got = os.listdir(out)
        print(f"[6] downloaded v{latest.version} artifact -> {got}")

    # ---- 7. delete the version (notebook: delete_model) and show the changelog audit trail
    manager.delete_model("ppo_cartpole_agent", latest.version, description="example cleanup")
    print(f"[7] deleted v{latest.version}")
    with open(os.path.join(registry_dir, "ppo_cartpole_agent", "CHANGELOG.md")) as f:
        print("\n--- CHANGELOG.md (the registry's audit trail) ---")
        print(f.read())
    print(f"registry dir: {registry_dir}")


if __name__ == "__main__":
    main()
