"""Template: the TPU-native decoupled actor-learner architecture.

The reference's template (examples/architecture_template.py) spawns
buffer/player/trainer *processes* joined by torch.distributed collectives. On a
single-controller JAX runtime the same architecture is a DEVICE split: one mesh
chip plays, the rest train, and the "collectives" are direct device-to-device
array placements — no process groups, no object pipes.

Run on the virtual CPU mesh (no TPU needed):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/architecture_template.py
"""

import os
import sys

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.core.runtime import Runtime
from sheeprl_tpu.parallel.decoupled import split_runtime


def main() -> None:
    runtime = Runtime(accelerator="cpu" if jax.device_count() < 2 else "auto", devices=8)
    player_rt, trainer_rt = split_runtime(runtime)
    print(f"player mesh: {player_rt.mesh}, trainer mesh: {trainer_rt.mesh}")

    # --- a toy "policy": y = x @ w ------------------------------------------------
    obs_dim, act_dim, batch = 16, 4, 32 * trainer_rt.world_size
    params = {"w": jnp.zeros((obs_dim, act_dim))}
    tx = optax.sgd(1e-2)
    opt_state = trainer_rt.replicate(tx.init(params))
    params = trainer_rt.replicate(params)

    data_sharding = NamedSharding(trainer_rt.mesh, P("data"))

    @jax.jit
    def train_step(params, opt_state, batch_x, batch_y):
        batch_x = jax.lax.with_sharding_constraint(batch_x, data_sharding)

        def loss_fn(p):
            return jnp.mean((batch_x @ p["w"] - batch_y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)  # psum inserted by XLA
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # --- player: rollouts on its own chip ----------------------------------------
    player_params = jax.device_put(params, player_rt.replicated)
    act = jax.jit(lambda p, x: x @ p["w"])

    rng = np.random.default_rng(0)
    for it in range(5):
        # 1) the player acts (dedicated chip, uncontended by training)
        obs = jax.device_put(rng.normal(size=(batch, obs_dim)).astype(np.float32), player_rt.replicated)
        actions = act(player_params, obs)

        # 2) the payload moves onto the trainer mesh (reference: scatter_object_list)
        target = jnp.ones((batch, act_dim))
        batch_x = jax.device_put(obs, trainer_rt.replicated)
        params, opt_state, loss = train_step(params, opt_state, batch_x, target)

        # 3) parameter refresh back to the player chip (reference: flattened-vector
        #    broadcast, ppo_decoupled.py:550-554)
        player_params = jax.device_put(params, player_rt.replicated)
        print(f"iter {it}: loss={float(loss):.4f}")

    print("done — see sheeprl_tpu/algos/ppo/ppo_decoupled.py for the full version")


if __name__ == "__main__":
    main()
