"""Benchmark entrypoint for the driver: prints ONE JSON line.

Two workloads, both on the real chip:

1. PPO env-steps/sec on CartPole-v1 (BASELINE.md target metric #1; headline
   ``value``). Reference anchor: 81.27 s for 65_536 steps on 4 CPUs => ~806
   env-steps/s (sheeprl v0.5.5 SB3 comparison table, README.md:99-115).
2. DreamerV3-S jitted train step at the Atari-100K shape (batch 16 x seq 64,
   64x64x3 pixels, bf16-mixed) — g-steps/s, replayed frames/s, and MFU
   (XLA-estimated FLOPs per step / elapsed / chip peak). Reference anchor:
   ~14 h for Atari-100K on an RTX 3080 (README.md:44-51) ≈ 1 g-step/s at
   replay_ratio 1 — reported as ``dv3_vs_baseline``.

Every record is also appended to the persistent cross-run ledger
(``benchmarks/ledger.jsonl`` or ``--ledger``/``$SHEEPRL_TPU_BENCH_LEDGER``),
and ``bench.py --check-regressions`` runs the regression sentinel over it:
the newest round's SPS/MFU/p99/peak-HBM metrics against the median of prior
same-status rounds with direction-aware per-metric thresholds, exiting 4 (and
emitting ``Regress/*`` rows) on a breach. See howto/observability.md.
"""

from __future__ import annotations

import contextlib
import json
import sys
import time

def _chip_peak_flops(device):
    # single source of truth for the per-chip bf16 peak table lives in the
    # telemetry fabric (imported lazily: bench must stay importable before the
    # backend-discovery watchdog has run)
    from sheeprl_tpu.telemetry.device import chip_peak_flops

    return chip_peak_flops(device)


def _provenance() -> dict:
    """run_id + git SHA + telemetry trace pointers stamped on every bench
    record, so a BENCH_r*.json row is attributable to the exact tree and trace
    that produced it (null-tolerant: a missing git binary or disabled tracer
    must never cost the measurement)."""
    import os
    import subprocess
    import uuid

    out = {"run_id": uuid.uuid4().hex[:12], "git_sha": None}
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True,
            text=True,
            timeout=10,
        )
        out["git_sha"] = sha.stdout.strip() or None
    except Exception:
        pass
    try:
        from sheeprl_tpu.telemetry import trace

        out["trace_id"] = trace.current_trace_id() or None
        out["trace_path"] = (
            trace.export(os.path.join("logs", "telemetry", f"bench_{out['run_id']}.trace.json"))
            if trace.enabled()
            else None
        )
    except Exception:
        out["trace_id"] = out["trace_path"] = None
    return out


def _ppo_pass(total_steps: int) -> float:
    from sheeprl_tpu.cli import run

    t0 = time.perf_counter()
    run(
        overrides=[
            "exp=ppo",
            f"algo.total_steps={total_steps}",
            "algo.rollout_steps=128",
            "algo.per_rank_batch_size=64",
            "env.num_envs=8",
            "env.sync_env=True",
            "env.capture_video=False",
            "algo.mlp_keys.encoder=[state]",
            "algo.run_test=False",
            "metric.log_level=0",
            "metric.disable_timer=True",
            "checkpoint.every=999999999",
            "checkpoint.save_last=False",
            "buffer.memmap=False",
        ]
    )
    return total_steps / (time.perf_counter() - t0)


def bench_ppo(total_steps: int = 65536, passes: int = 3) -> dict:
    """PPO throughput with variance control: one short warmup pass absorbs jit
    compilation, then ``passes`` full runs are timed and the MEDIAN reported
    with its spread.

    Single-pass numbers on the tunneled chip swung r2->r3 by 34% purely from
    cold-compile + tunnel-latency noise (see benchmarks/PPO_BENCH_NOTES.md);
    per-iteration cost here is ONE tunnel round-trip (~100-140 ms measured) for
    the on-policy params refresh, so wall-clock is latency- not compute-bound
    and needs a median over repeats to be comparable across rounds.
    """
    _ppo_pass(8192)  # warmup: compile the train/rollout jits outside the timed passes
    sps = sorted(_ppo_pass(total_steps) for _ in range(passes))
    median = sps[len(sps) // 2] if passes % 2 else 0.5 * (sps[passes // 2 - 1] + sps[passes // 2])
    baseline_sps = 65536 / 81.27  # reference PPO benchmark: 65536 steps / 81.27 s (README.md:99-115)
    return {
        "metric": "ppo_cartpole_env_steps_per_sec",
        "value": round(median, 2),
        "unit": "env-steps/s",
        "vs_baseline": round(median / baseline_sps, 3),
        "ppo_passes": [round(v, 2) for v in sps],
        "ppo_spread": round((sps[-1] - sps[0]) / 2.0, 2),
    }


_INGRAPH_COMMON = (
    "exp=ppo",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.run_test=False",
    # timers must stay on (they carry the rollout-phase split) => log_level=1;
    # the episode prints that come with it are swallowed by the devnull
    # redirect in _instrumented_ppo_pass, and log_every is pushed out of reach
    "metric.log_level=1",
    "metric.log_every=1000000000",
    "metric.disable_timer=False",
    "env.capture_video=False",
    "checkpoint.every=999999999",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
)


def _instrumented_ppo_pass(overrides, total_steps: int) -> dict:
    """One full PPO run returning wall-clock AND rollout-phase env-steps/s.

    The rollout-phase number comes from the loop's own ``Time/env_interaction_time``
    timer; the cli resets timers at every metric flush, so the reset is held
    open for the duration of the pass and the accumulated sum read afterwards.
    """
    import os

    from sheeprl_tpu.cli import run
    from sheeprl_tpu.utils.timer import timer

    saved_reset = timer.__dict__["reset"]
    saved_timers = timer.timers
    timer.reset = lambda: None  # accumulate across log flushes for this pass
    timer.timers = {}
    try:
        t0 = time.perf_counter()
        with open(os.devnull, "w") as devnull, contextlib.redirect_stdout(devnull):
            run(overrides=list(overrides))
        wall = time.perf_counter() - t0
        phase = timer.compute()
    finally:
        setattr(timer, "reset", saved_reset)
        timer.timers = saved_timers
    env_s = float(phase.get("Time/env_interaction_time") or 0.0)
    return {
        "wall_sps": total_steps / wall,
        "rollout_sps": (total_steps / env_s) if env_s > 0 else None,
    }


def _fused_collect_sps(num_envs: int, rollout_steps: int, iters: int = 8) -> float:
    """Sustained env-steps/s of the fused ``lax.scan`` collector alone, fenced.

    This exists because the train loop's ``Time/env_interaction_time`` timer
    cannot measure the in-graph backend: ``collector.collect()`` is an async
    dispatch, so the timer records microseconds of enqueue while the real work
    overlaps the train phase. Here the collector is driven standalone and each
    measurement is fenced with ``block_until_ready`` on the carry (every
    iteration consumes the previous carry, so fencing the last one fences the
    whole chain).
    """
    import jax

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.config import load_config
    from sheeprl_tpu.core.runtime import build_runtime
    from sheeprl_tpu.envs import ingraph as ig

    cfg = load_config(
        overrides=[
            "exp=ppo",
            "env=jax_cartpole",
            f"env.num_envs={num_envs}",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
        ]
    )
    runtime = build_runtime(cfg.fabric)
    venv = ig.make_vector_env(cfg, num_envs, 42, device=runtime.device)
    _, _, player = build_agent(runtime, (2,), False, cfg, venv.single_observation_space, None)
    player.params = jax.device_put(player.params, runtime.device)
    venv.reset(seed=42)
    collector = ig.InGraphRolloutCollector(
        venv, player, rollout_steps=rollout_steps, gamma=float(cfg.algo.gamma), name="bench"
    )
    collector.collect()  # compile + first rollout
    jax.block_until_ready(venv.carry.obs)
    t0 = time.perf_counter()
    for _ in range(iters):
        collector.collect()
    jax.block_until_ready(venv.carry.obs)
    return iters * rollout_steps * num_envs / (time.perf_counter() - t0)


def bench_ingraph(
    num_envs: int = 4096, rollout_steps: int = 128, iters: int = 8, host_steps: int = 16384
) -> dict:
    """In-graph vectorized backend (envs/ingraph/) vs the host gym path.

    Headline: sustained fused-collect env-steps/s (``policy.act ∘ env.step``
    under one ``lax.scan``, fenced — see :func:`_fused_collect_sps`), compared
    against the repo's standing host-path PPO baseline (the exact bench_ppo
    CartPole shape, full loop) as ``vs_baseline``. Context fields report the
    host run's rollout-phase split and a full ingraph training run's wall-clock
    env-steps/s; on the CPU fallback the latter is bounded by the shared train
    phase, not the collector.
    """
    host_over = list(_INGRAPH_COMMON) + [
        "algo.rollout_steps=128",
        "algo.per_rank_batch_size=64",
        "env.num_envs=8",
        "env.sync_env=True",
    ]
    _instrumented_ppo_pass(host_over + ["algo.total_steps=2048"], 2048)  # compile warmup
    host = _instrumented_ppo_pass(host_over + [f"algo.total_steps={host_steps}"], host_steps)

    steps_per_iter = num_envs * rollout_steps
    ingraph_over = list(_INGRAPH_COMMON) + [
        "env=jax_cartpole",
        f"env.num_envs={num_envs}",
        f"algo.rollout_steps={rollout_steps}",
        "algo.per_rank_batch_size=16384",
        "algo.update_epochs=1",
    ]
    # warmup pass seeds the persistent compile cache, so the timed pass's first
    # iteration replays executables instead of compiling them
    _instrumented_ppo_pass(ingraph_over + [f"algo.total_steps={steps_per_iter}"], steps_per_iter)
    total = steps_per_iter * iters
    ing = _instrumented_ppo_pass(ingraph_over + [f"algo.total_steps={total}"], total)

    collect_sps = _fused_collect_sps(num_envs, rollout_steps, iters=iters)
    host_full = host["wall_sps"]
    speedup = collect_sps / host_full
    return {
        "metric": "ingraph_env_steps_per_sec",
        "value": round(collect_sps, 2),
        "unit": "env-steps/s",
        "vs_baseline": round(speedup, 2),
        "ingraph_env_steps_per_sec": round(collect_sps, 2),
        "ingraph_vs_host_x": round(speedup, 2),
        "ingraph_host_full_loop_env_steps_per_sec": round(host_full, 2),
        "ingraph_host_rollout_phase_env_steps_per_sec": (
            round(host["rollout_sps"], 2) if host["rollout_sps"] else None
        ),
        "ingraph_train_loop_env_steps_per_sec": round(ing["wall_sps"], 2),
        "ingraph_num_envs": num_envs,
        "ingraph_rollout_steps": rollout_steps,
    }


def bench_ingraph_train(num_envs: int = 4096, rollout_steps: int = 128, iters: int = 4) -> dict:
    """Whole-iteration fused training (envs/ingraph/fused.py): rollout scan +
    GAE + update epochs in ONE donated-carry jitted program, driven standalone
    and fenced.

    Headline: aggregate env-steps/s of the fused iteration — env steps both
    collected AND trained on per wall-clock second. ``vs_baseline`` is the
    ratio against the same-session fused collect-only number (the PR-10
    ``--target ingraph`` headline): on a TPU slice, where the collect scan is
    dispatch/latency-bound, the update rides in the same program largely for
    free and the ratio approaches 1; on a CPU host the collect scan is already
    FLOP-bound, so the update's forward+backward over every collected row is
    pure added compute and the ratio reports exactly what the host pays for it.
    The update's wall-clock share per iteration is reported alongside. Design
    target on a v5e slice (howto/ingraph_envs.md): >= 1M aggregate env-steps/s.
    """
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import make_update_impl
    from sheeprl_tpu.config import instantiate, load_config
    from sheeprl_tpu.core.runtime import build_runtime
    from sheeprl_tpu.envs import ingraph as ig
    from sheeprl_tpu.utils.optim import with_clipping
    from sheeprl_tpu.utils.utils import PlayerParamsSync

    n_data = num_envs * rollout_steps
    cfg = load_config(
        overrides=[
            "exp=ppo",
            "env=jax_cartpole",
            f"env.num_envs={num_envs}",
            f"algo.rollout_steps={rollout_steps}",
            f"algo.per_rank_batch_size={n_data}",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
        ]
    )
    runtime = build_runtime(cfg.fabric)
    venv = ig.make_vector_env(cfg, num_envs, 42, device=runtime.device)
    agent, params, player = build_agent(runtime, (2,), False, cfg, venv.single_observation_space, None)
    player.params = jax.device_put(player.params, runtime.device)
    venv.reset(seed=42)
    collector = ig.InGraphRolloutCollector(
        venv, player, rollout_steps=rollout_steps, gamma=float(cfg.algo.gamma), name="bench"
    )
    tx = with_clipping(instantiate(dict(cfg.algo.optimizer))(), cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    params_sync = PlayerParamsSync(player.params)
    update_impl = make_update_impl(
        agent, tx, cfg, runtime, n_data, list(cfg.algo.mlp_keys.encoder), [], params_sync
    )
    trainer = ig.FusedInGraphTrainer(collector, update_impl, n_extras=3, name="bench")
    key = jax.random.PRNGKey(0)
    extras = (jnp.float32(cfg.algo.clip_coef), jnp.float32(cfg.algo.ent_coef), jnp.float32(1.0))

    def fused_step():
        nonlocal params, opt_state, key
        key, sub = jax.random.split(key)
        params, opt_state, _flat, _roll, _train = trainer.step(params, opt_state, sub, *extras)

    # same-session collect-only reference: identical env batch, policy, and
    # carry chain, minus the update — the difference IS the update's wall-clock.
    # A SEPARATE collector instance: lax.scan's jaxpr cache is keyed on the
    # scan-body function object, so tracing split ``collect`` and the fused
    # ``iteration`` over one collector's shared ``one_step`` closure replays
    # the first trace's captured param tracers into the second
    # (UnexpectedTracerError). Production loops trace only one per process.
    ref_collector = ig.InGraphRolloutCollector(
        venv, player, rollout_steps=rollout_steps, gamma=float(cfg.algo.gamma), name="bench_ref"
    )
    ref_collector.collect()
    jax.block_until_ready(venv.carry.obs)
    t0 = time.perf_counter()
    for _ in range(iters):
        ref_collector.collect()
    jax.block_until_ready(venv.carry.obs)
    collect_iter_s = (time.perf_counter() - t0) / iters
    collect_sps = n_data / collect_iter_s

    fused_step()  # compile + first iteration
    jax.block_until_ready(params)
    t0 = time.perf_counter()
    for _ in range(iters):
        fused_step()
    jax.block_until_ready(params)
    fused_iter_s = (time.perf_counter() - t0) / iters
    fused_sps = n_data / fused_iter_s

    return {
        "metric": "ingraph_fused_train_env_steps_per_sec",
        "value": round(fused_sps, 2),
        "unit": "env-steps/s",
        "vs_baseline": round(fused_sps / collect_sps, 3),
        "ingraph_fused_train_env_steps_per_sec": round(fused_sps, 2),
        "ingraph_fused_train_update_s_per_iter": round(max(fused_iter_s - collect_iter_s, 0.0), 4),
        "ingraph_fused_train_iter_s": round(fused_iter_s, 4),
        "ingraph_collect_only_env_steps_per_sec": round(collect_sps, 2),
        "ingraph_fused_train_num_envs": num_envs,
        "ingraph_fused_train_rollout_steps": rollout_steps,
        "ingraph_fused_train_tpu_slice_target_env_steps_per_sec": 1_000_000,
    }


def bench_telemetry(num_envs: int = 256, rollout_steps: int = 32, iters: int = 8, reps: int = 3) -> dict:
    """Span-tracer overhead on the fused PPO iteration, plus auto-computed MFU.

    Three interleaved variants of the same AOT-warmed fused loop: ``baseline``
    (no instrumentation calls at all), ``spans-off`` (the production span/
    instant seams present, tracer disabled — the zero-cost-when-disabled
    guarantee as a measured number), and ``spans-on`` (tracer recording into
    the ring). Interleaving reps A/B/C absorbs thermal/scheduler drift; the
    assertions use each variant's best-of (overhead is additive, so the
    fastest rep of each is the least-noise comparison):

    - spans-on must cost < 2% env-steps/s vs baseline,
    - spans-off must be indistinguishable from baseline (< 1%, i.e. 0 modulo
      measurement noise).

    MFU is computed, not hand-derived: the fused step's FLOPs come from
    ``lowered.compile().cost_analysis()`` captured by the retrace guard at
    AOT-warm time (core/compile.py), divided by measured iteration time and
    the chip's bf16 peak (telemetry/device.py) — null on chips with no peak
    table entry rather than fabricated.
    """
    import os
    import tempfile

    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import make_update_impl
    from sheeprl_tpu.config import instantiate, load_config
    from sheeprl_tpu.core.runtime import build_runtime
    from sheeprl_tpu.envs import ingraph as ig
    from sheeprl_tpu.telemetry import device as tel_device
    from sheeprl_tpu.telemetry import trace
    from sheeprl_tpu.utils.optim import with_clipping
    from sheeprl_tpu.utils.utils import PlayerParamsSync

    n_data = num_envs * rollout_steps
    cfg = load_config(
        overrides=[
            "exp=ppo",
            "env=jax_cartpole",
            f"env.num_envs={num_envs}",
            f"algo.rollout_steps={rollout_steps}",
            f"algo.per_rank_batch_size={n_data}",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
        ]
    )
    runtime = build_runtime(cfg.fabric)
    venv = ig.make_vector_env(cfg, num_envs, 42, device=runtime.device)
    agent, params, player = build_agent(runtime, (2,), False, cfg, venv.single_observation_space, None)
    player.params = jax.device_put(player.params, runtime.device)
    venv.reset(seed=42)
    collector = ig.InGraphRolloutCollector(
        venv, player, rollout_steps=rollout_steps, gamma=float(cfg.algo.gamma), name="bench_tel"
    )
    tx = with_clipping(instantiate(dict(cfg.algo.optimizer))(), cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    params_sync = PlayerParamsSync(player.params)
    update_impl = make_update_impl(
        agent, tx, cfg, runtime, n_data, list(cfg.algo.mlp_keys.encoder), [], params_sync
    )
    trainer = ig.FusedInGraphTrainer(collector, update_impl, n_extras=3, name="bench_tel")
    key = jax.random.PRNGKey(0)
    extras = (jnp.float32(cfg.algo.clip_coef), jnp.float32(cfg.algo.ent_coef), jnp.float32(1.0))
    st = {"params": params, "opt": opt_state, "key": key}

    def plain_step():
        st["key"], sub = jax.random.split(st["key"])
        st["params"], st["opt"], _flat, _roll, _train = trainer.step(st["params"], st["opt"], sub, *extras)

    def traced_step():
        # the production fused loop's per-iteration seams: one update span +
        # one instant (ppo.py wraps the fused step exactly like this)
        with trace.span("train/update", fused=True):
            plain_step()
        trace.instant("bench/iter")

    saved_env = os.environ.get(trace.ENV_VAR)
    trace.disable()
    # AOT-warm registers the executable AND captures its cost_analysis() FLOPs
    trainer.step_fn.aot_compile(
        *trainer.warmup_specs(st["params"], st["opt"], st["key"], *extras)
    )
    plain_step()  # first dispatch
    jax.block_until_ready(st["params"])

    def measure(step) -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            step()
        jax.block_until_ready(st["params"])
        return n_data * iters / (time.perf_counter() - t0)

    base, off, on = [], [], []
    try:
        for _ in range(reps):
            trace.disable()
            base.append(measure(plain_step))
            off.append(measure(traced_step))
            trace.configure(plane="train", capacity=65536)
            on.append(measure(traced_step))
        tel_stats = trace.stats()
        trace_path = trace.export(
            os.path.join(tempfile.mkdtemp(prefix="bench_telemetry_"), "trace.json")
        )
    finally:
        trace.disable()
        if saved_env is not None:
            os.environ[trace.ENV_VAR] = saved_env

    overhead_on = (max(base) / max(on) - 1.0) * 100.0
    overhead_off = (max(base) / max(off) - 1.0) * 100.0
    if overhead_on >= 2.0:
        raise RuntimeError(
            f"span tracer costs {overhead_on:.2f}% env-steps/s on the fused loop (budget: < 2%)"
        )
    if overhead_off >= 1.0:
        raise RuntimeError(
            f"DISABLED span seams cost {overhead_off:.2f}% env-steps/s (must be 0 within noise)"
        )
    step_flops = trainer.step_fn.last_step_flops
    iter_s = n_data / max(base)
    mfu = tel_device.mfu(step_flops, iter_s, runtime.device)
    med = lambda xs: sorted(xs)[len(xs) // 2]
    return {
        "telemetry_tracer_overhead_pct": round(overhead_on, 3),
        "telemetry_disabled_overhead_pct": round(overhead_off, 3),
        "telemetry_baseline_env_steps_per_sec": round(med(base), 2),
        "telemetry_spans_off_env_steps_per_sec": round(med(off), 2),
        "telemetry_spans_on_env_steps_per_sec": round(med(on), 2),
        "telemetry_spans_recorded": tel_stats.get("Telemetry/spans_recorded"),
        "telemetry_trace_export_path": trace_path,
        "telemetry_step_tflops": round(step_flops / 1e12, 4) if step_flops else None,
        "telemetry_mfu": round(mfu, 4) if mfu is not None else None,
        "telemetry_num_envs": num_envs,
        "telemetry_rollout_steps": rollout_steps,
        "telemetry_overhead_budget_pct": 2.0,
    }


def bench_dv3(
    batch: int = 128,
    seq: int = 64,
    iters: int = 20,
    extra_overrides=("algo.imagination_scan_unroll=15",),
    key_prefix: str = "dv3",
) -> dict:
    """Time the fused DreamerV3-S train step at the measured-best TPU config.

    Defaults follow scripts/mfu_sweep.py on the v5e: batch 128 with the H=15
    imagination scan fully unrolled measures ~27.7% MFU (XLA-estimated flops;
    the T=64 dynamic scan's flops are NOT trip-count-scaled by XLA cost
    analysis, so true model-flops MFU is higher — see
    benchmarks/DV3_MFU_NOTES.md). ``key_prefix`` lets a second call report the
    batch-16 Atari-100K recipe shape as ``dv3_recipe_*``."""
    import gymnasium as gym
    import jax
    import numpy as np

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_fn
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
    from sheeprl_tpu.config.loader import load_config
    from sheeprl_tpu.core.runtime import Runtime

    cfg = load_config(
        overrides=[
            "exp=dreamer_v3",
            "algo=dreamer_v3_S",
            "env=dummy",
            "fabric.precision=bf16-mixed",
            f"algo.per_rank_batch_size={batch}",
            f"algo.per_rank_sequence_length={seq}",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.cnn_keys.decoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.mlp_keys.decoder=[]",
            *extra_overrides,
        ]
    )
    runtime = Runtime(accelerator="auto", devices=1, precision=cfg.fabric.precision)
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8)})
    actions_dim = (6,)  # Atari-like discrete head (MsPacman has 9; 6 is the classic set)
    modules, params, _player = build_agent(runtime, actions_dim, False, cfg, obs_space)
    init_opt, train_fn = make_train_fn(modules, cfg, runtime, False, actions_dim)
    opt_states = runtime.replicate(init_opt(params))
    params = runtime.replicate(params)
    moments = init_moments()
    counter = np.int32(0)

    rng = np.random.default_rng(0)
    g, t, b, a = 1, seq, batch, int(np.sum(actions_dim))
    batches = {
        "rgb": jax.device_put(rng.integers(0, 255, (g, t, b, 3, 64, 64), dtype=np.uint8)),
        "actions": jax.device_put(rng.random((g, t, b, a), dtype=np.float32)),
        "rewards": jax.device_put(rng.random((g, t, b, 1), dtype=np.float32)),
        "terminated": jax.device_put(np.zeros((g, t, b, 1), dtype=np.float32)),
        "truncated": jax.device_put(np.zeros((g, t, b, 1), dtype=np.float32)),
        "is_first": jax.device_put(np.zeros((g, t, b, 1), dtype=np.float32)),
    }
    key = jax.random.PRNGKey(0)

    # XLA's own FLOP estimate for one compiled train step (model FLOPs for MFU)
    step_flops = None
    try:
        compiled = train_fn.lower(params, opt_states, moments, counter, batches, key).compile()
        cost = compiled.cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        step_flops = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass  # cost analysis is backend-dependent; MFU reported as null if absent

    # warmup (first call compiles / loads the cache). NOTE: on the tunneled TPU,
    # block_until_ready returns without waiting — only a real host pull (np.asarray
    # of a device scalar) synchronizes, so that is how the timing fences work.
    for _ in range(2):
        params, opt_states, moments, counter, _flat, _m = train_fn(params, opt_states, moments, counter, batches, key)
    np.asarray(counter)

    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_states, moments, counter, _flat, _m = train_fn(params, opt_states, moments, counter, batches, key)
    np.asarray(counter)  # counter is carried through every step: pulls the whole chain
    elapsed = time.perf_counter() - t0

    gsteps_per_sec = iters / elapsed
    sec_per_step = elapsed / iters
    peak = _chip_peak_flops(runtime.device)
    mfu = (step_flops / sec_per_step / peak) if (step_flops and peak) else None
    # hand-counted model FLOPs: XLA's cost_analysis counts scan bodies once
    # instead of x trip count (benchmarks/DV3_MFU_NOTES.md), so the analytic
    # figure is the honest numerator for MFU
    try:
        from benchmarks.analytic_flops import dv3_step_flops

        analytic_flops = dv3_step_flops(cfg, batch, seq, actions_dim)["total"]
    except Exception as e:  # pure-Python counter: a failure is a bug, make it visible
        print(f"analytic flop count failed: {type(e).__name__}: {e}", file=sys.stderr)
        analytic_flops = None
    mfu_analytic = (analytic_flops / sec_per_step / peak) if (analytic_flops and peak) else None
    return {
        f"{key_prefix}_gsteps_per_sec": round(gsteps_per_sec, 3),
        f"{key_prefix}_frames_per_sec": round(gsteps_per_sec * batch * seq, 1),
        f"{key_prefix}_step_tflops": round(step_flops / 1e12, 3) if step_flops else None,
        f"{key_prefix}_mfu": round(mfu, 4) if mfu is not None else None,
        f"{key_prefix}_step_tflops_analytic": round(analytic_flops / 1e12, 3) if analytic_flops else None,
        f"{key_prefix}_mfu_analytic": round(mfu_analytic, 4) if mfu_analytic is not None else None,
        f"{key_prefix}_device": getattr(runtime.device, "device_kind", str(runtime.device)),
        # reference anchor: ~1 g-step/s on RTX 3080 (Atari-100K in ~14h, README.md:44-51)
        f"{key_prefix}_vs_baseline": round(gsteps_per_sec / 1.0, 3),
    }


def bench_smoke(total_steps: int = 128) -> dict:
    """Tiny PPO pass on the CPU backend for BOTH buffer backends.

    Exists so the bench harness itself is exercised by the test suite (as a
    non-slow test) while the accelerator tunnel is down: every BENCH_*.json
    round since r2 failed on reachability, which also meant nobody would notice
    the harness bit-rotting. Runs on the dummy env, a 16-step rollout, and both
    ``buffer.backend=host`` and ``buffer.backend=device`` so the on-policy HBM
    rollout path is covered too; a third pass over async env workers engages the
    interaction pipeline (core/pipeline.py) and reports the env-step time hidden
    behind device/host work. Numbers are NOT comparable to the real bench.
    """
    from sheeprl_tpu.cli import run
    from sheeprl_tpu.core.pipeline import process_overlap_totals

    result = {
        "metric": _target_metric("smoke"),
        "unit": "env-steps/s",
        "smoke": True,
    }
    common = [
        "exp=ppo",
        f"algo.total_steps={total_steps}",
        "algo.rollout_steps=16",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=1",
        "env=dummy",
        "env.num_envs=2",
        "env.capture_video=False",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.run_test=False",
        "metric.log_level=0",
        "metric.disable_timer=True",
        "checkpoint.every=999999999",
        "checkpoint.save_last=False",
        "buffer.memmap=False",
        "fabric.devices=1",
    ]
    for backend in ("host", "device"):
        t0 = time.perf_counter()
        run(overrides=[*common, "env.sync_env=True", f"buffer.backend={backend}"])
        result[f"smoke_{backend}_env_steps_per_sec"] = round(
            total_steps / (time.perf_counter() - t0), 2
        )
    # async env workers: the pipelined pass (Time/sps_pipeline_overlap's source)
    overlap_s0, overlap_n0 = process_overlap_totals()
    t0 = time.perf_counter()
    run(overrides=[*common, "env.sync_env=False", "buffer.backend=host"])
    result["smoke_pipeline_env_steps_per_sec"] = round(total_steps / (time.perf_counter() - t0), 2)
    overlap_s, overlap_n = process_overlap_totals()
    result["smoke_pipeline_overlap_s"] = round(overlap_s - overlap_s0, 3)
    result["smoke_pipeline_overlap_steps"] = overlap_n - overlap_n0
    if overlap_s > overlap_s0:
        result["smoke_sps_pipeline_overlap"] = round(
            (overlap_n - overlap_n0) * 2 / (overlap_s - overlap_s0), 2
        )
    result["value"] = result["smoke_host_env_steps_per_sec"]
    return result


_COMPILE_CHILD = r"""
import contextlib, json, os, sys, time
t0 = time.perf_counter()
from sheeprl_tpu.cli import run
from sheeprl_tpu.core import compile as jax_compile

overrides = json.loads(os.environ["_SHEEPRL_BENCH_COMPILE_OVERRIDES"])
with contextlib.redirect_stdout(sys.stderr):
    run(overrides=overrides)
stats = jax_compile.process_stats()
train = jax_compile.find("ppo.train")
print("BENCH_COMPILE " + json.dumps({
    "wall_s": round(time.perf_counter() - t0, 3),
    "first_train_step_s": round(train.first_call_s, 3) if train and train.first_call_s else None,
    "cache_hits": stats["cache_hits"],
    "cache_misses": stats["cache_misses"],
    "compile_seconds": round(stats["compile_seconds"], 3),
    "retraces": stats["retraces"],
}), flush=True)
"""


def bench_compile(total_steps: int = 64) -> dict:
    """Cold-vs-warm persistent-cache wall clock + time-to-first-train-step.

    Runs the same tiny PPO workload twice in FRESH subprocesses against one
    temporary on-disk compilation cache: the cold child populates it, the warm
    child replays it. Subprocesses are the only honest measurement — in-process
    repeats would hit jit's in-memory trace cache and time nothing. The child
    reports ``first_train_step_s`` from the retrace guard's own first-call
    clock (core/compile.py GuardedFn.first_call_s), i.e. process start ->
    first fused train step returning, the latency the AOT warmup + persistent
    cache exist to shrink.
    """
    import json as _json
    import os
    import subprocess
    import tempfile

    overrides = [
        "exp=ppo",
        f"algo.total_steps={total_steps}",
        "algo.rollout_steps=16",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=1",
        "env=dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.run_test=False",
        "metric.log_level=0",
        "metric.disable_timer=True",
        "checkpoint.every=999999999",
        "checkpoint.save_last=False",
        "buffer.memmap=False",
        "fabric.devices=1",
    ]
    result = {}
    with tempfile.TemporaryDirectory(prefix="sheeprl_bench_cache_") as cache_dir:
        env = dict(
            os.environ,
            SHEEPRL_TPU_COMP_CACHE_DIR=cache_dir,
            SHEEPRL_TPU_COMP_CACHE_MIN_SECS="0",
            _SHEEPRL_BENCH_COMPILE_OVERRIDES=_json.dumps(overrides),
        )
        for phase in ("cold", "warm"):
            proc = subprocess.run(
                [sys.executable, "-c", _COMPILE_CHILD], env=env, capture_output=True, text=True, timeout=1200
            )
            line = next((ln for ln in proc.stdout.splitlines() if ln.startswith("BENCH_COMPILE ")), None)
            if proc.returncode != 0 or line is None:
                result[f"compile_{phase}_error"] = (proc.stderr or proc.stdout)[-500:]
                return result
            child = _json.loads(line[len("BENCH_COMPILE "):])
            result[f"compile_{phase}_wall_s"] = child["wall_s"]
            result[f"compile_{phase}_first_train_step_s"] = child["first_train_step_s"]
            result[f"compile_{phase}_cache_hits"] = child["cache_hits"]
            result[f"compile_{phase}_cache_misses"] = child["cache_misses"]
            result[f"compile_{phase}_compile_seconds"] = child["compile_seconds"]
            result[f"compile_{phase}_retraces"] = child["retraces"]
    if result.get("compile_cold_wall_s") and result.get("compile_warm_wall_s"):
        result["compile_warm_speedup"] = round(
            result["compile_cold_wall_s"] / result["compile_warm_wall_s"], 3
        )
    return result


def bench_health() -> dict:
    """Self-healing runtime drill: detection latency + rollback wall clock.

    Reuses the scripts/health_smoke.py scenario (chaos reward-spike PPO run:
    the sentinel must detect the divergence, climb warn -> backoff -> rollback,
    restore a certified checkpoint, and complete). The numbers measure the
    health machinery itself — the smoke child runs on the CPU backend, so they
    are comparable across rounds but say nothing about accelerator throughput.
    """
    import importlib.util
    import os
    import tempfile

    spec = importlib.util.spec_from_file_location(
        "health_smoke",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts", "health_smoke.py"),
    )
    health_smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(health_smoke)

    t0 = time.perf_counter()
    smoke = health_smoke.main(tempfile.mkdtemp(prefix="bench_health_"))
    return {
        "health_detection_latency_s": smoke["detection_latency_s"],
        "health_detection_latency_steps": smoke["detection_latency_steps"],
        "health_rollback_wall_s": smoke["rollback_wall_s"],
        "health_rollbacks": smoke["rollbacks"],
        "health_certified_sidecars": smoke["certified_sidecars"],
        "health_drill_wall_s": round(time.perf_counter() - t0, 3),
    }


def bench_orchestrate() -> dict:
    """Elastic-population drill: preemption-recovery latency + resow wall clock.

    Reuses the scripts/population_smoke.py fleet chaos drill (two PPO trials on
    two preemptible slots: controller kill-and-restart, two injected slot
    preemptions, one ChaosEnv divergence resown from the clean peer's certified
    checkpoint). Recovery latency is SIGTERM-exit to respawn of the resumed
    incarnation; resow wall is divergence verdict to the resown spawn. Both
    measure the orchestration machinery on the CPU backend — comparable across
    rounds, silent about accelerator throughput.
    """
    import importlib.util
    import os
    import tempfile

    spec = importlib.util.spec_from_file_location(
        "population_smoke",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts", "population_smoke.py"),
    )
    population_smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(population_smoke)

    t0 = time.perf_counter()
    smoke = population_smoke.main(tempfile.mkdtemp(prefix="bench_orchestrate_"))
    return {
        "orchestrate_preempt_recovery_s": smoke["preempt_recovery_latency_s"],
        "orchestrate_preempt_recoveries": smoke["preempt_recovery_latencies_s"],
        "orchestrate_resow_wall_s": smoke["resow_wall_s"],
        "orchestrate_injections": smoke["injections"],
        "orchestrate_controller_incarnations": smoke["controller_incarnations"],
        "orchestrate_drill_wall_s": round(time.perf_counter() - t0, 3),
    }


def bench_transport(iters: int = 60, chunk_bytes: int = 8192) -> dict:
    """Host control-plane drill: collective latency + chunk-stream throughput.

    Runs a KVServer with two ControlPlane peers in-process (threads, real
    sockets — the same path scripts/transport_smoke.py drills across
    processes) and measures broadcast/barrier round-trips, the epoch-fenced
    chunk stream clean, and the SAME stream again under a 10% deterministic
    drop failpoint (``control.chunk_send:drop:prob=0.1;seed=7``) so the
    retry/resend overhead is a number, not a hope. CPU-backend machinery
    numbers — comparable across rounds, silent about the accelerator.
    """
    import threading

    from sheeprl_tpu.core import failpoints
    from sheeprl_tpu.parallel.control import ControlPlane, KVServer, SocketKV

    server = KVServer()
    server.start()
    try:
        p0 = ControlPlane(SocketKV(server.address), rank=0, world=2, scope="bench", timeout_ms=60_000)
        p1 = ControlPlane(SocketKV(server.address), rank=1, world=2, scope="bench", timeout_ms=60_000)
        payload = b"x" * chunk_bytes

        def timed_pair(fn0, fn1, n):
            samples = []

            def side(fn):
                fn()

            for _ in range(n):
                t0 = time.perf_counter()
                t = threading.Thread(target=side, args=(fn1,))
                t.start()
                fn0()
                t.join()
                samples.append((time.perf_counter() - t0) * 1000.0)
            samples.sort()
            return samples[len(samples) // 2]

        bcast_ms = timed_pair(
            lambda: p0.broadcast_str("b", "v"), lambda: p1.broadcast_str("b"), iters
        )
        barrier_ms = timed_pair(lambda: p0.barrier("t"), lambda: p1.barrier("t"), iters)

        def stream(channel, spec=None):
            p0.begin_session(channel)
            p1.adopt_epoch(channel)
            resends0 = p0.counters["Resilience/chunk_resends"]

            def send():
                if spec:
                    with failpoints.active(spec):
                        for i in range(iters):
                            p0.send_chunk(channel, i, payload)
                else:
                    for i in range(iters):
                        p0.send_chunk(channel, i, payload)

            t = threading.Thread(target=send)
            t0 = time.perf_counter()
            t.start()
            for i in range(iters):
                p1.recv_chunk(channel, i)
            t.join()
            wall = time.perf_counter() - t0
            return wall, p0.counters["Resilience/chunk_resends"] - resends0

        clean_wall, clean_resends = stream("clean")
        drop_wall, drop_resends = stream("drop", "control.chunk_send:drop:prob=0.1;seed=7")
        return {
            "transport_broadcast_p50_ms": round(bcast_ms, 3),
            "transport_barrier_p50_ms": round(barrier_ms, 3),
            "transport_chunk_roundtrip_ms": round(clean_wall / iters * 1000.0, 3),
            "transport_chunk_mb_per_s": round(iters * chunk_bytes / clean_wall / 1e6, 3),
            "transport_clean_resends": clean_resends,
            "transport_drop_resends": drop_resends,
            "transport_drop_overhead_x": round(drop_wall / clean_wall, 3),
            "transport_chunk_bytes": chunk_bytes,
            "transport_iters": iters,
        }
    finally:
        server.stop()


def _serve_level(addr, obs: dict, qps: float, duration_s: float) -> dict:
    """One open-loop load level: send at the offered rate WITHOUT waiting for
    responses (a closed-loop client would never overrun the server, hiding the
    backpressure behavior the sweep exists to show), collect latencies on a
    reader thread, report percentiles + terminal-status mix."""
    import json as _json
    import socket
    import threading

    sent: dict = {}
    latencies: list = []
    statuses: dict = {}
    lock = threading.Lock()
    sock = socket.create_connection(addr, timeout=10.0)
    rw = sock.makefile("rwb")

    def reader():
        while True:
            try:
                line = rw.readline()
            except (OSError, ValueError):
                return
            if not line:
                return
            resp = _json.loads(line)
            t1 = time.monotonic()
            with lock:
                t0 = sent.pop(resp.get("id"), None)
                statuses[resp["status"]] = statuses.get(resp["status"], 0) + 1
                if resp.get("status") == "ok" and t0 is not None:
                    latencies.append((t1 - t0) * 1000.0)

    rt = threading.Thread(target=reader, daemon=True)
    rt.start()
    n = max(1, int(qps * duration_s))
    interval = 1.0 / qps
    t_start = time.monotonic()
    for i in range(n):
        target_t = t_start + i * interval
        now = time.monotonic()
        if target_t > now:
            time.sleep(target_t - now)
        rid = f"q{qps}-{i}"
        with lock:
            sent[rid] = time.monotonic()
        rw.write((_json.dumps({"id": rid, "obs": obs}) + "\n").encode())
        rw.flush()
    send_elapsed = time.monotonic() - t_start
    settle_until = time.monotonic() + 10.0
    while time.monotonic() < settle_until:
        with lock:
            if not sent:
                break
        time.sleep(0.02)
    with lock:
        unresolved = len(sent)
    sock.close()
    rt.join(timeout=2.0)
    latencies.sort()
    pct = lambda p: round(latencies[min(len(latencies) - 1, int(len(latencies) * p))], 3) if latencies else None
    return {
        "offered_qps": qps,
        "achieved_qps": round(n / send_elapsed, 1),
        "sent": n,
        "ok": statuses.get("ok", 0),
        "rejected": statuses.get("rejected", 0),
        "shed": statuses.get("shed", 0),
        "deadline_missed": statuses.get("deadline_expired", 0),
        "errors": statuses.get("error", 0),
        "unresolved": unresolved,
        "p50_ms": pct(0.50),
        "p99_ms": pct(0.99),
    }


def bench_serve(qps_levels=(25, 50, 100, 200), duration_s: float = 3.0) -> dict:
    """Policy-serving QPS sweep: offered load vs p50/p99 latency.

    Reuses the scripts/serve_smoke.py fixture (tiny certified PPO checkpoint,
    subprocess server) and drives an open-loop generator at each offered QPS
    level. The sweep's invariant — asserted, not just reported — is ZERO
    retraces after warmup: every request mix lands on an AOT bucket. Headline
    ``serve_p99_ms`` is the p99 at the highest offered level.
    """
    import importlib.util
    import os
    import signal
    import subprocess
    import tempfile

    spec = importlib.util.spec_from_file_location(
        "serve_smoke",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts", "serve_smoke.py"),
    )
    serve_smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(serve_smoke)

    t0 = time.perf_counter()
    workdir = tempfile.mkdtemp(prefix="bench_serve_")
    fixture = serve_smoke.build_fixture(workdir)
    ready_file = os.path.join(workdir, "ready.json")
    stats_file = os.path.join(workdir, "stats.json")
    log_file = os.path.join(workdir, "server.log")
    proc = serve_smoke.launch_server(fixture, ready_file, stats_file, log_file)
    result: dict = {}
    try:
        info = serve_smoke.wait_ready(ready_file, proc, log_file, timeout=240.0)
        addr = (info["host"], info["port"])
        levels = [_serve_level(addr, fixture["obs"], qps, duration_s) for qps in qps_levels]
        stats = serve_smoke.rpc(addr, {"op": "stats"})
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=90)
    finally:
        if proc.poll() is None:
            proc.kill()
    retraces = stats.get("Compile/retraces")
    if retraces != 0:
        raise RuntimeError(f"{retraces} steady-state retraces during the QPS sweep (must be 0)")
    result["serve_levels"] = levels
    result["serve_retraces"] = retraces
    result["serve_aot_compiles"] = stats.get("Compile/aot_compiles")
    result["serve_batch_occupancy"] = stats.get("Serve/batch_occupancy")
    top = levels[-1]
    result["serve_p50_ms"] = top["p50_ms"]
    result["serve_p99_ms"] = top["p99_ms"]
    result["serve_offered_qps"] = top["offered_qps"]
    result["serve_sweep_wall_s"] = round(time.perf_counter() - t0, 3)
    return result


def bench_serve_fleet(
    qps_levels=(25, 50, 100), duration_s: float = 3.0, slo_p99_ms: float = 750.0
) -> dict:
    """Fleet availability sweep: offered-QPS levels THROUGH the failover
    router while the fleet is being abused — one replica SIGKILLed before the
    second level, a rolling certified deploy landing across the later levels —
    with an asserted p99 SLO and zero client-visible errors/losses at every
    level. This is the serving plane's availability number: what a client pays
    in tail latency for a crash plus a weight rollout, instead of an outage.

    Reuses scripts/serve_fleet_smoke.py's launcher (3 real serve replicas +
    supervisor subprocess). Headline ``serve_fleet_p99_ms`` is the p99 of the
    final post-deploy level at the top offered rate; ``serve_fleet_worst_p99_ms``
    (what the SLO gates) is the worst p99 across ALL chaos levels.
    """
    import importlib.util
    import os
    import signal
    import tempfile

    spec = importlib.util.spec_from_file_location(
        "serve_fleet_smoke",
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts", "serve_fleet_smoke.py"
        ),
    )
    fleet_smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet_smoke)
    serve_smoke = fleet_smoke.serve_smoke

    t0 = time.perf_counter()
    workdir = tempfile.mkdtemp(prefix="bench_fleet_")
    fixture = serve_smoke.build_fixture(workdir)
    fleet_dir = os.path.join(workdir, "fleet")
    ready_file = os.path.join(workdir, "router_ready.json")
    stats_file = os.path.join(workdir, "fleet_stats.json")
    log_file = os.path.join(workdir, "fleet.log")
    proc = fleet_smoke.launch_fleet(fixture, fleet_dir, ready_file, stats_file, log_file)
    result: dict = {}
    levels = []
    try:
        info = serve_smoke.wait_ready(ready_file, proc, log_file, timeout=600.0)
        addr = (info["host"], info["port"])

        def fleet_stats():
            return serve_smoke.rpc(addr, {"op": "stats"})

        levels.append(dict(_serve_level(addr, fixture["obs"], qps_levels[0], duration_s), chaos="baseline"))
        # chaos 1: SIGKILL one replica, then offer the next level while the
        # router fails over and the supervisor respawns the slot
        members = fleet_smoke.read_membership(os.path.join(fleet_dir, "membership.json"))
        os.kill(int(members[-1]["pid"]), signal.SIGKILL)
        for qps in qps_levels[1:]:
            levels.append(dict(_serve_level(addr, fixture["obs"], qps, duration_s), chaos="post_kill"))
        # chaos 2: certify a new generation and hold the top offered rate
        # while the rolling deploy drains/reboots replicas one at a time
        serve_smoke.write_generation(
            fixture["ckpt_dir"], serve_smoke.perturb(fixture["state"]), 200
        )
        deadline = time.monotonic() + 600.0
        while fleet_stats().get("Fleet/deploys", 0) < 1:
            if time.monotonic() > deadline:
                raise RuntimeError("rolling deploy never landed during the fleet sweep")
            levels.append(
                dict(_serve_level(addr, fixture["obs"], qps_levels[-1], duration_s), chaos="during_deploy")
            )
        levels.append(
            dict(_serve_level(addr, fixture["obs"], qps_levels[-1], duration_s), chaos="post_deploy")
        )
        stats = fleet_stats()
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
    # SLO gate — asserted, not just reported: chaos may cost tail latency and
    # sheds, never errors, losses, or an SLO breach
    worst_p99 = max(lv["p99_ms"] for lv in levels if lv["p99_ms"] is not None)
    for lv in levels:
        if lv["errors"] or lv["unresolved"]:
            raise RuntimeError(
                f"fleet sweep level {lv['chaos']}@{lv['offered_qps']}qps saw "
                f"{lv['errors']} errors / {lv['unresolved']} unresolved (must be 0)"
            )
    if worst_p99 > slo_p99_ms:
        raise RuntimeError(
            f"fleet sweep p99 {worst_p99:.1f} ms breached the {slo_p99_ms:.0f} ms SLO"
        )
    if stats.get("Fleet/replica_restarts", 0) < 1:
        raise RuntimeError("the SIGKILLed replica was never respawned during the sweep")
    top = levels[-1]
    result["serve_fleet_levels"] = levels
    result["serve_fleet_p50_ms"] = top["p50_ms"]
    result["serve_fleet_p99_ms"] = top["p99_ms"]
    result["serve_fleet_worst_p99_ms"] = round(worst_p99, 3)
    result["serve_fleet_slo_p99_ms"] = slo_p99_ms
    result["serve_fleet_qps"] = top["achieved_qps"]
    result["serve_fleet_restarts"] = stats.get("Fleet/replica_restarts")
    result["serve_fleet_deploys"] = stats.get("Fleet/deploys")
    result["serve_fleet_failovers"] = stats.get("Fleet/failovers")
    result["serve_fleet_fenced_writes"] = stats.get("Fleet/fenced_writes")
    result["serve_fleet_members"] = stats.get("Fleet/members")
    result["serve_fleet_sweep_wall_s"] = round(time.perf_counter() - t0, 3)
    return result


def bench_rssm(
    batch: int = 16,
    seq_len: int = 64,
    iters: int = 3,
    stochastic: int = 16,
    discrete: int = 16,
    recurrent: int = 256,
    dense_units: int = 256,
    hidden: int = 256,
    action: int = 6,
    embed: int = 256,
) -> dict:
    """Fused RSSM step-kernel microbench: flax scan vs the fused formulation.

    Compiles ``value_and_grad`` of a scalar loss over the full dynamic scan for
    both paths (``kernels=off`` -> flax reference; ``kernels=reference`` -> the
    fused step with its hand-written ``custom_vjp``) at the same shapes, via
    ``guarded_jit`` + ``aot_compile`` so both programs land in the compiled-
    program ledger and carry cost_analysis numbers. The headline is the fused
    path's ``bytes accessed`` per scan step — the custom_vjp keeps only the
    scan's own carries/xs as residuals and recomputes every intermediate in the
    backward pass, so its memory traffic must sit >= 25% below the flax scan,
    whose autodiff stacks per-step intermediates across T (ISSUE 16 acceptance
    gate; CPU-measurable, the cost model is backend-portable). Defaults are the
    Atari-100K training recipe scan shape (batch 16 x seq 64): parameter reads
    amortize across the scan there, so the residual-traffic reduction is the
    signal — short scans dilute it under per-step weight re-reads.

    v5e design target: at the walker_walk XL shape (R=4096, 32x32 stochastic)
    the same traffic reduction is what pushes the DV3 train step toward MFU
    0.45 on v5e-8 — the wall-clock column here is CPU-only context, not the
    accelerator number.
    """
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v3.agent import MLPWithHead, RecurrentModel, RSSM
    from sheeprl_tpu.core import compile as jax_compile

    sd = stochastic * discrete
    rm = RecurrentModel(
        input_size=action + sd,
        recurrent_state_size=recurrent,
        dense_units=dense_units,
        layer_norm=True,
        layer_norm_eps=1e-3,
    )
    rep = MLPWithHead(
        input_dim=embed + recurrent,
        hidden_sizes=[hidden],
        output_dim=sd,
        activation="silu",
        layer_norm=True,
        layer_norm_eps=1e-3,
    )
    trans = MLPWithHead(
        input_dim=recurrent,
        hidden_sizes=[hidden],
        output_dim=sd,
        activation="silu",
        layer_norm=True,
        layer_norm_eps=1e-3,
    )

    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4, k5, k6, k7 = jax.random.split(key, 7)
    wm_params = {
        "recurrent_model": rm.init(k1, jnp.zeros((batch, action + sd)), jnp.zeros((batch, recurrent))),
        "representation_model": rep.init(k2, jnp.zeros((batch, embed + recurrent))),
        "transition_model": trans.init(k3, jnp.zeros((batch, recurrent))),
        "initial_recurrent_state": 0.1 * jax.random.normal(k4, (recurrent,)),
    }
    emb = jax.random.normal(k5, (seq_len, batch, embed))
    act = jax.random.normal(k6, (seq_len, batch, action))
    isf = jnp.zeros((seq_len, batch, 1)).at[0].set(1.0)

    def _loss_for(kernels: str):
        rssm = RSSM(
            rm, rep, trans, stochastic_size=stochastic, discrete_size=discrete,
            unimix=0.01, kernels=kernels,
        )

        def loss(params, embedded, actions, is_first, rng):
            h, post, prior_l, post_l = rssm.dynamic_scan(params, embedded, actions, is_first, rng)
            return (
                jnp.mean(jnp.square(h))
                + jnp.mean(jnp.square(post))
                + jnp.mean(jnp.square(prior_l))
                + jnp.mean(jnp.square(post_l))
            )

        return jax.value_and_grad(loss)

    result = {
        "rssm_shape": f"B{batch}xT{seq_len} S{stochastic}xD{discrete} R{recurrent} DU{dense_units}",
        "rssm_backend": jax.default_backend(),
        "rssm_bytes_reduction_target_pct": 25.0,
        "rssm_v5e_mfu_target": 0.45,
    }
    specs = tuple(
        jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), a)
        for a in (wm_params, emb, act, isf, k7)
    )
    for label, kernels in (("flax", "off"), ("fused", "reference")):
        gfn = jax_compile.guarded_jit(_loss_for(kernels), name=f"bench.rssm_{label}")
        t0 = time.perf_counter()
        gfn.aot_compile(*specs)
        result[f"rssm_{label}_compile_s"] = round(time.perf_counter() - t0, 3)
        if gfn.last_step_bytes is not None:
            result[f"rssm_{label}_bytes_per_step"] = round(gfn.last_step_bytes / seq_len, 1)
        if gfn.last_step_flops is not None:
            result[f"rssm_{label}_flops_per_step"] = round(gfn.last_step_flops / seq_len, 1)
        # warm pass, then the timed median-free mean (CPU context number only)
        jax.block_until_ready(gfn(wm_params, emb, act, isf, k7))
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(gfn(wm_params, emb, act, isf, k7))
        dt = (time.perf_counter() - t0) / iters
        result[f"rssm_{label}_scan_ms"] = round(dt * 1e3, 3)
        result[f"rssm_{label}_steps_per_sec"] = round(seq_len / dt, 1)
    flax_b = result.get("rssm_flax_bytes_per_step")
    fused_b = result.get("rssm_fused_bytes_per_step")
    if flax_b and fused_b:
        result["rssm_bytes_reduction_pct"] = round((1.0 - fused_b / flax_b) * 100.0, 2)
        result["rssm_bytes_gate_pass"] = bool(
            result["rssm_bytes_reduction_pct"] >= result["rssm_bytes_reduction_target_pct"]
        )
    return result


def _fsdp_child_main(iters: int = 5) -> dict:
    """The in-process body of ``bench.py --target fsdp`` (see :func:`bench_fsdp`).

    Runs inside a subprocess pinned to an 8-device virtual CPU mesh
    (``--xla_force_host_platform_device_count=8`` must be in XLA_FLAGS before
    jax initializes — which is why the parent cannot run this inline). Three
    arms over the same tiny MLP regression step:

    - **handoff**: ``parallel/handoff.shard_put`` byte accounting for a
      rollout-shaped payload vs the replicated ``device_put`` path — the
      headline ``fsdp_handoff_bytes_per_iter`` and the strict
      ``sharded < replicated`` acceptance gate.
    - **ddp vs fsdp**: jitted donated-carry train step with replicated vs
      parameter-sharded (``Runtime.place_params``) state — step time and
      device-0 param+opt footprint.
    - **overlap**: the same update inside the portable ``shard_map`` shim with
      ``overlap.accumulate_grads`` at 1 vs 4 microbatches (per-bucket psum) —
      the gradient-sync overlap arm. All programs compile through
      ``guarded_jit`` so the pinned program ledger records their collective
      op counts/bytes (the HLO auditor's rows come back in the result).
    """
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sheeprl_tpu.core import compile as jax_compile
    from sheeprl_tpu.core.runtime import Runtime
    from sheeprl_tpu.data.device_buffer import _shard_map
    from sheeprl_tpu.parallel import handoff, overlap
    from sheeprl_tpu.telemetry import programs as tel_programs

    out: dict = {"fsdp_devices": jax.device_count(), "fsdp_backend": jax.default_backend()}
    out["fsdp_xla_profile_applied"] = overlap.apply_xla_profile("overlap")

    # ---- tiny MLP regression step (shared by every arm)
    D, H, B = 256, 512, 512
    rng = np.random.default_rng(0)
    # master copies stay HOST numpy: on the CPU backend device_put aliases a
    # same-process jax buffer zero-copy, so a donated placed copy would delete
    # the master under the next arm's feet
    params = {
        "w1": (rng.standard_normal((D, H)) * 0.02).astype(np.float32),
        "b1": np.zeros((H,), np.float32),
        "w2": (rng.standard_normal((H, H)) * 0.02).astype(np.float32),
        "b2": np.zeros((H,), np.float32),
        "w3": (rng.standard_normal((H, D)) * 0.02).astype(np.float32),
        "b3": np.zeros((D,), np.float32),
    }
    tx = optax.adam(1e-3)
    batch = {
        "x": rng.standard_normal((B, D)).astype(np.float32),
        "y": rng.standard_normal((B, D)).astype(np.float32),
    }

    def loss_fn(p, b):
        h = jax.nn.relu(b["x"] @ p["w1"] + p["b1"])
        h = jax.nn.relu(h @ p["w2"] + p["b2"])
        pred = h @ p["w3"] + p["b3"]
        return jnp.mean(jnp.square(pred - b["y"])), ()

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    # ---- arm 1: per-shard handoff bytes on a rollout-shaped payload
    T, E = 16, 64
    payload = {
        "obs": rng.standard_normal((T, E, 128)).astype(np.float32),
        "actions": rng.standard_normal((T, E, 6)).astype(np.float32),
        "values": rng.standard_normal((T, E, 1)).astype(np.float32),
        "rewards": rng.standard_normal((T, E, 1)).astype(np.float32),
        "dones": np.zeros((T, E, 1), np.float32),
    }
    rt = Runtime(accelerator="cpu", devices=8, strategy="auto", precision="32-true")
    handoff.reset_stats()
    sharded = handoff.shard_put(payload, rt.mesh, batch_axis=1)
    jax.block_until_ready(sharded)
    st = handoff.stats()
    replicated_bytes = handoff.replicated_put_bytes(payload, rt.mesh)
    out["fsdp_handoff_bytes_per_iter"] = int(st["put_bytes"])
    out["fsdp_handoff_puts_per_iter"] = int(st["puts"])
    out["fsdp_handoff_replicated_bytes_per_iter"] = int(replicated_bytes)
    out["fsdp_handoff_reduction_x"] = round(replicated_bytes / max(st["put_bytes"], 1), 2)
    # acceptance gate: the sharded handoff must move STRICTLY fewer bytes than
    # the replicated path it replaces
    out["fsdp_handoff_gate_pass"] = bool(st["put_bytes"] < replicated_bytes)

    # ---- arm 2: ddp vs fsdp step time + device-0 param/opt footprint
    dev0 = rt.mesh.devices.ravel()[0]

    def _dev0_mb(tree) -> float:
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            if isinstance(leaf, jax.Array):
                for s in leaf.addressable_shards:
                    if s.device == dev0:
                        total += s.data.nbytes
        return round(total / 1e6, 3)

    def step(p, o, b):
        (loss, _), grads = grad_fn(p, b)
        updates, o = tx.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return p, o, loss

    def _fresh(tree):
        # defensive copy: the placed state is donated, and a zero-copy
        # device_put must never hand the master's memory to the donation
        return jax.tree_util.tree_map(np.array, tree)

    for strategy in ("auto", "fsdp"):
        srt = Runtime(accelerator="cpu", devices=8, strategy=strategy, precision="32-true")
        p = srt.place_params(_fresh(params))
        o = srt.place_params(tx.init(_fresh(params)))
        b = handoff.shard_put(batch, srt.mesh, batch_axis=0)
        label = "ddp" if strategy == "auto" else "fsdp"
        gfn = jax_compile.guarded_jit(step, name=f"bench.fsdp_step_{label}", donate_argnums=(0, 1))
        # AOT so the program lands in the pinned ledger with the HLO collective audit
        gfn.aot_compile(jax_compile.specs_of(p), jax_compile.specs_of(o), jax_compile.specs_of(b))
        p, o, loss = gfn(p, o, b)  # warm
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            p, o, loss = gfn(p, o, b)
        jax.block_until_ready(loss)
        out[f"fsdp_{label}_step_ms"] = round((time.perf_counter() - t0) / iters * 1e3, 3)
        out[f"fsdp_{label}_dev0_param_opt_mb"] = _dev0_mb((p, o))
    if out.get("fsdp_ddp_dev0_param_opt_mb"):
        out["fsdp_vs_ddp_mem_x"] = round(
            out["fsdp_ddp_dev0_param_opt_mb"] / max(out["fsdp_fsdp_dev0_param_opt_mb"], 1e-9), 2
        )

    # ---- arm 3: gradient-sync overlap (microbatched per-bucket psum) at
    # 1 vs 4 microbatches inside the portable shard_map shim
    mesh = rt.mesh
    for m in (1, 4):

        def overlap_body(p, o, b, _m=m):
            (loss, _), grads = overlap.accumulate_grads(
                grad_fn, p, b, microbatches=_m, axis_name="data", axis_size=8
            )
            updates, o = tx.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return p, o, jax.lax.pmean(loss, "data")

        sm = _shard_map(
            overlap_body, mesh=mesh,
            in_specs=(P(), P(), P("data")), out_specs=(P(), P(), P()),
        )
        gfn = jax_compile.guarded_jit(sm, name=f"bench.fsdp_overlap_m{m}", donate_argnums=(0, 1))
        p = rt.place_params(_fresh(params))
        o = rt.place_params(tx.init(_fresh(params)))
        b = handoff.shard_put(batch, mesh, batch_axis=0)
        gfn.aot_compile(jax_compile.specs_of(p), jax_compile.specs_of(o), jax_compile.specs_of(b))
        p, o, loss = gfn(p, o, b)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            p, o, loss = gfn(p, o, b)
        jax.block_until_ready(loss)
        key = "fsdp_overlap_step_ms" if m == 4 else "fsdp_overlap_m1_step_ms"
        out[key] = round((time.perf_counter() - t0) / iters * 1e3, 3)

    # ---- HLO collective audit: every mesh program above landed in the pinned
    # program ledger (SHEEPRL_TPU_PROGRAMS, set by the parent) with the
    # auditor's collective dict — surface the per-program summary
    collective = {}
    for row in tel_programs.snapshot():
        col = row.get("collective")
        if col and row.get("name", "").startswith("bench.fsdp"):
            collective[row["name"]] = {
                "op_count": col.get("op_count"),
                "bytes": col.get("bytes"),
                "async_pairs": col.get("async_pairs"),
                "sync_ops": col.get("sync_ops"),
            }
    if collective:
        out["fsdp_collective"] = collective
        out["fsdp_collective_bytes_total"] = int(
            sum(c.get("bytes") or 0 for c in collective.values())
        )
    return out


def bench_fsdp(iters: int = 5, timeout_s: float = 600.0) -> dict:
    """DDP-vs-FSDP-vs-overlap step time + per-shard handoff bytes (ISSUE 18).

    Folds the retired ``scripts/fsdp_bench.py`` into the sentinel-gated bench:
    the measurement runs in a SUBPROCESS pinned to an 8-device virtual CPU
    mesh (``--xla_force_host_platform_device_count`` only takes effect before
    jax initializes) with a private compiled-program ledger, so the HLO
    collective auditor's rows come back with the timings. Headline:
    ``fsdp_handoff_bytes_per_iter`` (sentinel class ``handoff_bytes``,
    direction *lower*) — the bytes the donated per-shard rollout handoff
    actually moves, vs the replicated path's ``mesh_size x`` copy.
    """
    import os
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        xla = env.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in xla:
            env["XLA_FLAGS"] = (xla + " --xla_force_host_platform_device_count=8").strip()
        env["SHEEPRL_TPU_PROGRAMS"] = os.path.join(td, "programs.jsonl")
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env["_SHEEPRL_BENCH_FSDP_CHILD"] = str(int(iters))
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(repo, "bench.py")],
                env=env, capture_output=True, text=True, timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            return {"fsdp_error": f"child exceeded {timeout_s}s"}
        for line in proc.stdout.splitlines():
            if line.startswith("FSDP_BENCH "):
                try:
                    return json.loads(line[len("FSDP_BENCH "):])
                except ValueError:
                    break
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-8:]
        return {"fsdp_error": f"child rc={proc.returncode}: " + " | ".join(tail)}


def _ckpt_child_main(reps: int = 3) -> dict:
    """Subprocess body of bench_checkpoint, pinned to the 8-device CPU mesh.

    Four timings on the SAME ~48 MiB mesh-sharded state:

    1. ``checkpoint_legacy_blocked_ms`` — the synchronous single-file
       ``save_state`` (the caller eats serialize + fsync);
    2. ``checkpoint_blocked_save_ms`` — the async sharded path's train-thread
       block (D2H snapshot only; serialize/fsync/commit ride the writer
       thread). The acceptance gate: strictly below legacy;
    3. ``checkpoint_commit_visible_ms`` — save() call to committed-and-
       discoverable (the window a preemption loses);
    4. ``checkpoint_elastic_restore_s`` / ``checkpoint_peer_restore_s`` —
       8-device save restored onto a 2-device mesh, and the peer-RAM fetch
       (control-plane chunk stream, zero storage reads) of the same payload.
    """
    import pickle
    import tempfile
    import threading

    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    import sheeprl_tpu.utils.ckpt_sharded as cs
    from sheeprl_tpu.utils.checkpoint import save_state

    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("d",))
    rng = np.random.default_rng(0)
    state = {
        "params": {
            f"layer{i}": jax.device_put(
                rng.standard_normal((1024, 1536)).astype(np.float32),
                NamedSharding(mesh, PartitionSpec("d")),
            )
            for i in range(8)
        },
        "step": 1,
    }
    jax.block_until_ready(state["params"])
    state_bytes = sum(leaf.nbytes for leaf in state["params"].values())

    def median(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    out: dict = {"checkpoint_state_mb": round(state_bytes / 1e6, 1), "checkpoint_reps": reps}
    with tempfile.TemporaryDirectory() as td:
        legacy_ms = []
        for r in range(reps):
            t0 = time.perf_counter()
            save_state(os.path.join(td, f"legacy_{r}.ckpt"), state)
            legacy_ms.append((time.perf_counter() - t0) * 1e3)

        blocked_ms, visible_ms = [], []
        ck = cs.ShardedCheckpointer(process_index=0, world=1)
        try:
            last_path = None
            for r in range(reps):
                last_path = os.path.join(td, f"sharded_{r}.ckpt")
                t0 = time.perf_counter()
                pending = ck.save(last_path, state)
                blocked_ms.append(pending.blocked_s * 1e3)
                pending.wait(120.0)
                visible_ms.append((time.perf_counter() - t0) * 1e3)
                assert cs.is_committed(last_path)
        finally:
            ck.close()

        mesh_b = Mesh(np.array(devices[:2]), ("d",))
        t0 = time.perf_counter()
        restored = cs.elastic_restore(
            last_path,
            lambda key, shape, dtype: NamedSharding(mesh_b, PartitionSpec("d"))
            if key.startswith("/params/")
            else None,
        )
        jax.block_until_ready(restored["params"])
        out["checkpoint_elastic_restore_s"] = round(time.perf_counter() - t0, 3)

        # peer-RAM emergency path: two in-process control planes, real sockets
        from sheeprl_tpu.parallel.control import ControlPlane, KVServer, SocketKV

        payload = pickle.dumps(jax.device_get(state), protocol=pickle.HIGHEST_PROTOCOL)
        server = KVServer()
        server.start()
        try:
            p0 = ControlPlane(SocketKV(server.address), rank=0, world=2, scope="ckptbench", timeout_ms=60_000)
            p1 = ControlPlane(SocketKV(server.address), rank=1, world=2, scope="ckptbench", timeout_ms=60_000)
            p0.begin_session("ckpt_replicator")
            store = cs.PeerReplicaStore(p1, src_rank=0, poll_ms=20, fence_role="ckpt_replicator")
            store.start()
            push = threading.Thread(
                target=cs.replicate_to_peer, args=(p0, payload, 1), kwargs={"timeout_ms": 60_000}
            )
            push.start()
            push.join()
            # the restarted incarnation of rank 0 fetches its own snapshot back
            p0b = ControlPlane(SocketKV(server.address), rank=0, world=2, scope="ckptbench", timeout_ms=60_000)
            t0 = time.perf_counter()
            fetched = cs.fetch_from_peer(p0b, timeout_ms=60_000)
            assert fetched is not None and fetched[0] == 1
            pickle.loads(fetched[1])
            out["checkpoint_peer_restore_s"] = round(time.perf_counter() - t0, 3)
            store.stop()
            store.join(timeout=5.0)
        finally:
            server.stop()

    out["checkpoint_legacy_blocked_ms"] = round(median(legacy_ms), 3)
    out["checkpoint_blocked_save_ms"] = round(median(blocked_ms), 3)
    out["checkpoint_commit_visible_ms"] = round(median(visible_ms), 3)
    out["checkpoint_blocked_reduction_x"] = round(
        median(legacy_ms) / max(median(blocked_ms), 1e-6), 2
    )
    # acceptance gate: the async sharded path must block the train thread
    # STRICTLY less than the legacy synchronous save it replaces
    out["checkpoint_gate_pass"] = bool(median(blocked_ms) < median(legacy_ms))
    return out


def bench_checkpoint(reps: int = 3, timeout_s: float = 600.0) -> dict:
    """Sharded-checkpoint subsystem drill (elastic-checkpointing issue).

    Runs in a SUBPROCESS pinned to an 8-device virtual CPU mesh (the
    device-count flag only takes effect before jax initializes). Headline:
    ``checkpoint_blocked_save_ms`` (sentinel class ``blocked_save``, direction
    *lower*) — the milliseconds the training thread stalls per checkpoint,
    which the async writer reduces to the D2H snapshot alone."""
    import os
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        xla = env.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in xla:
            env["XLA_FLAGS"] = (xla + " --xla_force_host_platform_device_count=8").strip()
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env["TMPDIR"] = td
        env["_SHEEPRL_BENCH_CKPT_CHILD"] = str(int(reps))
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(repo, "bench.py")],
                env=env, capture_output=True, text=True, timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            return {"checkpoint_error": f"child exceeded {timeout_s}s"}
        for line in proc.stdout.splitlines():
            if line.startswith("CKPT_BENCH "):
                try:
                    return json.loads(line[len("CKPT_BENCH "):])
                except ValueError:
                    break
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-8:]
        return {"checkpoint_error": f"child rc={proc.returncode}: " + " | ".join(tail)}


def bench_population(
    members: int = 8,
    envs_per_member: int = 8,
    epochs: int = 4,
    iters_per_epoch: int = 4,
    rollout_steps: int = 8,
    timeout_s: float = 600.0,
) -> dict:
    """Device-resident vmapped population vs the subprocess-per-trial fleet.

    Three subprocess children on the CPU backend, same training budget
    (``members x epochs x iters x rollout x envs`` env-steps):

    1. ``population.backend=fused`` on ONE device — the whole PBT population
       as one compiled vmapped program (orchestrate/fused_trainee.py); the
       headline ``population_agg_env_steps_per_sec`` is its aggregate
       training throughput, and ``population_fused_wall_s`` its wall clock
       including the single jax import + compile;
    2. the same fused program on a FORCED 8-device virtual mesh (member axis
       shard_map'd onto ``data``, one member's full train loop per device) —
       ``population_shard_scaling_x`` is its aggregate throughput over a
       1-member/1-device run's, the member-axis scaling factor (near-linear =
       approaching ``members``; the 8-member/1-device vmapped run is NOT the
       base because XLA already spreads its batched ops across the same
       physical cores);
    3. the classic subprocess backend: ``members`` independent trials on
       ``members`` slots through the real controller, each paying its own
       interpreter + jax import + compile — exactly the overhead the fused
       backend deletes. ``population_fused_speedup_x`` (wall/wall, sentinel
       class ``fused_speedup``) is the ISSUE 19 >=2x acceptance gate.
    """
    import os
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    steps_per_member = epochs * iters_per_epoch * rollout_steps * envs_per_member
    base_overrides = [
        "exp=ppo",
        "env=jax_cartpole",
        "metric.log_level=0",
        f"algo.rollout_steps={rollout_steps}",
        "algo.per_rank_batch_size=32",
        "algo.update_epochs=1",
        "seed=7",
    ]
    pop_spec = {
        "backend": "fused",
        "members": members,
        "envs_per_member": envs_per_member,
        "epochs": epochs,
        "iters_per_epoch": iters_per_epoch,
        "checkpoint_every": epochs,  # one certified slice set per run
        "domain_rand": True,
        "overrides": base_overrides,
    }

    def _child_env(devices: int = 1) -> dict:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("SHEEPRL_TPU_FAILPOINTS", None)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        if devices > 1:
            xla = env.get("XLA_FLAGS", "")
            if "--xla_force_host_platform_device_count" not in xla:
                env["XLA_FLAGS"] = (
                    xla + f" --xla_force_host_platform_device_count={devices}"
                ).strip()
        return env

    def _run_fused(td: str, tag: str, devices: int, n_members: int = None) -> dict:
        spec = dict(pop_spec, devices=devices)
        if n_members is not None:
            spec["members"] = n_members
        spec_path = os.path.join(td, f"{tag}.json")
        with open(spec_path, "w") as f:
            json.dump({"orchestrate": {"population": spec}}, f)
        t0 = time.perf_counter()
        proc = subprocess.run(
            [
                sys.executable, "-m", "sheeprl_tpu.orchestrate.fused_trainee",
                "--spec", spec_path, "--state-dir", os.path.join(td, tag),
            ],
            env=_child_env(devices), capture_output=True, text=True, timeout=timeout_s,
        )
        wall = time.perf_counter() - t0
        for line in proc.stdout.splitlines():
            if line.startswith("POPULATION_FUSED "):
                summary = json.loads(line[len("POPULATION_FUSED "):])
                summary["bench_wall_s"] = round(wall, 3)
                return summary
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-8:]
        raise RuntimeError(f"fused child ({tag}) rc={proc.returncode}: " + " | ".join(tail))

    def _run_subprocess_fleet(td: str) -> float:
        trial_overrides = base_overrides + [
            f"env.num_envs={envs_per_member}",
            "fabric.devices=1",
            f"algo.total_steps={steps_per_member}",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "algo.run_test=False",
            "buffer.memmap=False",
            f"checkpoint.every={steps_per_member // epochs}",
            "checkpoint.save_last=False",
        ]
        spec = {
            "orchestrate": {
                "slots": members,  # maximum parallelism: the baseline's best case
                "poll_interval_s": 0.2,
                "resow": {"enabled": False},
                "exploit": {"interval_s": 0.0},
            },
            "trials": [
                {
                    "key": f"t{i:02d}",
                    "overrides": trial_overrides + [f"seed={7 + i}"],
                    "hyperparams": {"algo.optimizer.lr": 1e-3},
                }
                for i in range(members)
            ],
        }
        spec_path = os.path.join(td, "subprocess_fleet.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f)
        t0 = time.perf_counter()
        proc = subprocess.run(
            [
                sys.executable, "-m", "sheeprl_tpu.orchestrate.controller",
                "--spec", spec_path, "--state-dir", os.path.join(td, "subprocess_fleet"),
            ],
            env=_child_env(), capture_output=True, text=True, timeout=timeout_s,
        )
        wall = time.perf_counter() - t0
        result_line = next(
            (l for l in reversed(proc.stdout.splitlines()) if l.startswith("ORCHESTRATE_RESULT ")),
            None,
        )
        if proc.returncode != 0 or result_line is None:
            tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-8:]
            raise RuntimeError(f"subprocess fleet rc={proc.returncode}: " + " | ".join(tail))
        summary = json.loads(result_line.split("ORCHESTRATE_RESULT ", 1)[1])
        if summary.get("status") != "done":
            raise RuntimeError(f"subprocess fleet did not finish: {summary}")
        return wall

    out: dict = {
        "population_members": members,
        "population_env_steps": members * steps_per_member,
    }
    with tempfile.TemporaryDirectory(prefix="bench_population_") as td:
        fused = _run_fused(td, "fused_1dev", devices=1)
        out["population_agg_env_steps_per_sec"] = fused["agg_env_steps_per_s"]
        out["population_fused_wall_s"] = fused["bench_wall_s"]
        out["population_fused_train_wall_s"] = fused["train_wall_s"]
        out["population_fused_retraces"] = fused["retraces"]
        out["population_fused_exploits"] = fused["exploits"]
        out["population_fused_swaps"] = fused["swaps"]
        try:
            single = _run_fused(td, "fused_m1", devices=1, n_members=1)
            out["population_single_member_env_steps_per_sec"] = single["agg_env_steps_per_s"]
            # the forced-8-device child is occasionally signal-killed on a
            # loaded shared host — one retry before giving up on the scaling
            # numbers (the headline is already banked above)
            for attempt in (0, 1):
                try:
                    mesh = _run_fused(td, f"fused_8dev_a{attempt}", devices=8)
                    break
                except (RuntimeError, subprocess.TimeoutExpired):
                    if attempt:
                        raise
            out["population_mesh_agg_env_steps_per_sec"] = mesh["agg_env_steps_per_s"]
            out["population_mesh_world_size"] = mesh["world_size"]
            out["population_shard_scaling_x"] = round(
                mesh["agg_env_steps_per_s"] / max(single["agg_env_steps_per_s"], 1e-9), 3
            )
        except Exception as e:  # mesh child failure must not cost the headline
            out["population_mesh_error"] = f"{type(e).__name__}: {e}"
        try:
            sub_wall = _run_subprocess_fleet(td)
            out["population_subprocess_wall_s"] = round(sub_wall, 3)
            out["population_fused_speedup_x"] = round(
                sub_wall / max(fused["bench_wall_s"], 1e-9), 3
            )
        except Exception as e:
            out["population_subprocess_error"] = f"{type(e).__name__}: {e}"
    return out


def _target_metric(target: str) -> str:
    """Headline metric name for a bench target — the watchdog's failure record
    must name the metric the selected target WOULD have produced, not hardcode
    the PPO one (advisor r5 finding: a dv3-only failure record claiming
    ``ppo_cartpole_env_steps_per_sec`` misfiles the regression history)."""
    return {
        "ppo": "ppo_cartpole_env_steps_per_sec",
        "dv3": "dv3_gsteps_per_sec",
        "compile": "compile_warm_first_train_step_s",
        "health": "health_detection_latency_s",
        "orchestrate": "orchestrate_preempt_recovery_s",
        "serve": "serve_p99_ms",
        "serve_fleet": "serve_fleet_p99_ms",
        "transport": "transport_chunk_roundtrip_ms",
        "ingraph": "ingraph_env_steps_per_sec",
        "ingraph_train": "ingraph_fused_train_env_steps_per_sec",
        "telemetry": "telemetry_tracer_overhead_pct",
        "rssm": "rssm_fused_bytes_per_step",
        "fsdp": "fsdp_handoff_bytes_per_iter",
        "checkpoint": "checkpoint_blocked_save_ms",
        "population": "population_agg_env_steps_per_sec",
        "smoke": "ppo_smoke_env_steps_per_sec",
        "all": "ppo_cartpole_env_steps_per_sec",  # PPO stays the headline value
    }[target]


# unit for each headline metric: the watchdog's error record used to GUESS
# from the metric name ("env_steps" in it or not), which filed seconds- and
# milliseconds-unit targets as "g-steps/s" (see BENCH_r05.json's null row)
_METRIC_UNITS = {
    "ppo_cartpole_env_steps_per_sec": "env-steps/s",
    "dv3_gsteps_per_sec": "g-steps/s",
    "compile_warm_first_train_step_s": "s",
    "health_detection_latency_s": "s",
    "orchestrate_preempt_recovery_s": "s",
    "serve_p99_ms": "ms",
    "serve_fleet_p99_ms": "ms",
    "transport_chunk_roundtrip_ms": "ms",
    "ingraph_env_steps_per_sec": "env-steps/s",
    "ingraph_fused_train_env_steps_per_sec": "env-steps/s",
    "telemetry_tracer_overhead_pct": "%",
    "rssm_fused_bytes_per_step": "bytes/step",
    "fsdp_handoff_bytes_per_iter": "bytes/iter",
    "checkpoint_blocked_save_ms": "ms",
    "population_agg_env_steps_per_sec": "env-steps/s",
    "ppo_smoke_env_steps_per_sec": "env-steps/s",
}


# ---------------------------------------------------------------------------
# Cross-run regression sentinel (persistent ledger + --check-regressions)
# ---------------------------------------------------------------------------

_LEDGER_ENV = "SHEEPRL_TPU_BENCH_LEDGER"

# Direction-aware sentinel classes: key-substring -> (direction, default
# threshold fraction vs the median of prior rounds). Throughput and MFU must
# not fall; latencies, peak HBM, and overhead must not grow. Thresholds are
# per-class because the metrics' noise floors differ by an order of magnitude
# (SPS medians are stable to ~10%; p99 latency on a shared host is not).
_SENTINEL_CLASSES = (
    ("_per_sec", "higher", 0.10),
    ("mfu", "higher", 0.10),
    # achieved fleet throughput under chaos: an open-loop generator on a shared
    # host undershoots its offered rate noisily, hence the loose floor
    ("_qps", "higher", 0.25),
    ("_p99_ms", "lower", 0.25),
    ("_p50_ms", "lower", 0.25),
    ("hbm_peak", "lower", 0.05),
    ("overhead_pct", "lower", 0.50),
    # cost-model bytes are deterministic per (shape, compiler) — any growth is
    # a real fusion/residual regression, so the threshold is tight
    ("bytes_per_step", "lower", 0.02),
    # per-shard handoff bytes are pure payload-shape arithmetic — growth means
    # a leaf fell off the sharded path back onto the replicated one
    ("handoff_bytes", "lower", 0.02),
    # train-thread checkpoint stall: a D2H memcpy on a shared CPU host is
    # noisy, but growth past the floor means work leaked back onto the caller
    ("blocked_save", "lower", 0.50),
    # fused-population wall-clock advantage over the subprocess fleet: both
    # sides run on a shared CPU host, so the floor is loose — but the >=2x
    # acceptance gate means even a 25% slip is worth flagging
    ("fused_speedup", "higher", 0.25),
)


def _ledger_path(override=None) -> str:
    import os

    return (
        override
        or os.environ.get(_LEDGER_ENV)
        or os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "ledger.jsonl")
    )


def _append_ledger(result: dict, path=None) -> None:
    """Append this round's record to the persistent cross-run ledger. Never
    raises — losing a history row must not cost the measurement or the
    one-JSON-line stdout contract."""
    import os

    from sheeprl_tpu.core import failpoints

    path = _ledger_path(path)
    try:
        if failpoints.failpoint("bench.ledger_append", path=path) is failpoints.DROPPED:
            return
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(result) + "\n")
    except Exception:
        pass


def _read_bench_ledger(path: str) -> list:
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict):
                    rows.append(row)
    except OSError:
        pass
    return rows


def check_regressions(ledger: str, thresholds: dict | None = None) -> tuple:
    """The cross-run sentinel: compare the NEWEST ledger round's sentinel
    metrics (SPS/MFU/p99/peak-HBM classes above) against the median of every
    prior round that carries the same ``status`` (an ``ok`` round is never
    judged against ``cpu_fallback`` history). Returns ``(report, rc)`` where
    the report carries one ``Regress/<metric>`` row per checked metric and rc
    is 4 on any breach — the CI-gate contract."""
    import statistics

    thresholds = thresholds or {}
    rows = _read_bench_ledger(ledger)
    report = {
        "metric": "bench_regression_sentinel",
        "ledger": ledger,
        "rounds_total": len(rows),
        "checked": 0,
        "regressions": [],
        "status": "ok",
    }
    if len(rows) < 2:
        report["status"] = "skipped"
        report["skip_reason"] = f"need >= 2 ledger rounds to compare, have {len(rows)}"
        report["value"] = 0
        return report, 0
    current = rows[-1]
    status = current.get("status", "ok")
    prior = [r for r in rows[:-1] if r.get("status", "ok") == status]
    if not prior:
        report["status"] = "skipped"
        report["skip_reason"] = f"no prior rounds with status={status!r} to compare against"
        report["value"] = 0
        return report, 0
    report["rounds_prior"] = len(prior)
    report["current_run_id"] = current.get("run_id")
    for key in sorted(current):
        val = current[key]
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        cls = next(((d, t) for sub, d, t in _SENTINEL_CLASSES if sub in key), None)
        if cls is None:
            continue
        direction, thr = cls
        thr = float(thresholds.get(key, thr))
        hist = [
            float(r[key])
            for r in prior
            if isinstance(r.get(key), (int, float)) and not isinstance(r.get(key), bool)
        ]
        if not hist:
            continue
        med = statistics.median(hist)
        if med == 0:
            continue
        delta_pct = (float(val) - med) / abs(med) * 100.0
        if direction == "higher":
            breach = float(val) < med * (1.0 - thr)
        else:
            breach = float(val) > med * (1.0 + thr)
        report["checked"] += 1
        report[f"Regress/{key}"] = {
            "current": float(val),
            "median_prior": med,
            "n_prior": len(hist),
            "delta_pct": round(delta_pct, 2),
            "threshold_pct": round(thr * 100.0, 2),
            "direction": direction,
            "breach": bool(breach),
        }
        if breach:
            report["regressions"].append(key)
    report["value"] = len(report["regressions"])
    report["unit"] = "regressions"
    if report["regressions"]:
        report["status"] = "regressed"
    return report, (4 if report["regressions"] else 0)


def _parse_thresholds(entries) -> dict:
    out = {}
    for entry in entries or []:
        key, _, frac = entry.partition("=")
        try:
            out[key.strip()] = float(frac)
        except ValueError:
            raise SystemExit(f"--threshold expects KEY=FRACTION, got {entry!r}")
    return out


def _regression_check(result: dict) -> None:
    """Compare this run's PPO median against the newest BENCH_r*.json on disk.

    The r2->r3 'regression' was single-pass noise nobody could classify at the
    time (benchmarks/PPO_BENCH_NOTES.md); with the median+spread in hand, a
    real regression is now a median below the previous record by more than the
    measured spread — recorded in the JSON so the next round starts with a
    verdict instead of a mystery.
    """
    import glob
    import os
    import re

    try:
        here = os.path.dirname(os.path.abspath(__file__))
        numbered = []
        for p in glob.glob(os.path.join(here, "BENCH_r*.json")):
            m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(p))
            if m:
                numbered.append((int(m.group(1)), p))
        if not numbered:
            return
        with open(max(numbered)[1]) as f:
            prev = json.load(f)
        prev = prev.get("parsed", prev)
        prev_value = float(prev.get("value"))
        spread = float(result.get("ppo_spread") or 0.0)
        result["ppo_prev_round"] = prev_value
        if "ppo_spread" in prev:
            # both sides are warm medians with spreads: a confident verdict
            result["ppo_regressed"] = bool(
                result["value"] + spread < prev_value - float(prev.get("ppo_spread") or 0.0)
            )
        else:
            # the previous round is a single cold pass with documented ~34% noise
            # (benchmarks/PPO_BENCH_NOTES.md) — record the comparison, refuse the verdict
            result["ppo_regressed"] = None
    except Exception:
        # a broken/odd historical file must never cost the PPO number or the
        # one-JSON-line stdout contract
        return


if __name__ == "__main__":
    import argparse
    import os

    if os.environ.get("_SHEEPRL_BENCH_FSDP_CHILD"):
        # subprocess body of bench_fsdp: the parent set XLA_FLAGS for the
        # 8-device virtual mesh and a pinned program ledger before spawning us
        print("FSDP_BENCH " + json.dumps(_fsdp_child_main(int(os.environ["_SHEEPRL_BENCH_FSDP_CHILD"]))))
        sys.exit(0)

    if os.environ.get("_SHEEPRL_BENCH_CKPT_CHILD"):
        # subprocess body of bench_checkpoint: the parent pinned the CPU
        # backend and the 8-device virtual mesh before spawning us
        print("CKPT_BENCH " + json.dumps(_ckpt_child_main(int(os.environ["_SHEEPRL_BENCH_CKPT_CHILD"]))))
        sys.exit(0)

    parser = argparse.ArgumentParser(description="sheeprl-tpu bench harness (one JSON line on stdout)")
    parser.add_argument(
        "--target",
        choices=(
            "ppo",
            "dv3",
            "compile",
            "health",
            "orchestrate",
            "serve",
            "serve_fleet",
            "transport",
            "ingraph",
            "ingraph_train",
            "telemetry",
            "rssm",
            "fsdp",
            "checkpoint",
            "population",
            "all",
        ),
        default="all",
        help="which workload(s) to run on the accelerator",
    )
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="tiny CPU-backend PPO pass over both buffer backends (harness self-test; "
        "no accelerator, no comparable numbers)",
    )
    parser.add_argument(
        "--platform",
        choices=("auto", "cpu", "tpu", "gpu"),
        default="auto",
        help="pin JAX_PLATFORMS instead of backend auto-discovery (auto keeps jax's "
        "own probing; cpu skips the accelerator tunnel entirely)",
    )
    parser.add_argument(
        "--check-regressions",
        action="store_true",
        help="run NO workload: compare the newest ledger round's SPS/MFU/p99/peak-HBM "
        "against the median of prior rounds and exit 4 on a breach (the CI gate)",
    )
    parser.add_argument(
        "--ledger",
        default=None,
        help=f"persistent cross-run ledger path (default: benchmarks/ledger.jsonl next "
        f"to bench.py, or ${_LEDGER_ENV})",
    )
    parser.add_argument(
        "--threshold",
        action="append",
        default=[],
        metavar="METRIC=FRACTION",
        help="per-metric sentinel threshold override for --check-regressions "
        "(repeatable; e.g. --threshold serve_p99_ms=0.5)",
    )
    cli_args = parser.parse_args()

    if cli_args.check_regressions:
        # a pure ledger read: no backend discovery, no watchdog, no jax import
        report, rc = check_regressions(
            _ledger_path(cli_args.ledger), _parse_thresholds(cli_args.threshold)
        )
        print(json.dumps(report))
        sys.exit(rc)
    headline_metric = _target_metric("smoke" if cli_args.smoke else cli_args.target)

    if cli_args.platform != "auto":
        os.environ["JAX_PLATFORMS"] = cli_args.platform
    elif cli_args.smoke:
        # the smoke pass must not depend on (or wait for) the tunneled chip
        os.environ["JAX_PLATFORMS"] = "cpu"

    # An unreachable accelerator must not hang the driver's bench step (a dead
    # tunnel parks every device RPC forever — seen in round 5 when the relay
    # process died): probe backend discovery under a watchdog. On timeout the
    # process re-execs itself pinned to JAX_PLATFORMS=cpu so the run still
    # produces real (if slow) numbers instead of a null record; a second
    # timeout on the CPU fallback is unrecoverable and emits the error record.
    import threading

    probe_done = threading.Event()

    def _watchdog():
        if not probe_done.wait(180):
            if os.environ.get("JAX_PLATFORMS") == "cpu":
                print(
                    json.dumps(
                        {
                            "metric": headline_metric,
                            "value": None,
                            "unit": _METRIC_UNITS.get(headline_metric, "s"),
                            "vs_baseline": None,
                            "status": "skipped",
                            "skip_reason": "backend discovery exceeded 180s even on the CPU "
                            "fallback (broken jax install?)",
                        }
                    ),
                    flush=True,
                )
                # rc 0: the "skipped" status row IS the result — a hard rc=3
                # here turned an environment problem into a bench-step failure
                # for the whole run (see BENCH_r05.json)
                os._exit(0)
            print(
                "WARNING: accelerator unreachable (backend discovery exceeded 180s, "
                "tunnel/relay down?) — falling back to JAX_PLATFORMS=cpu",
                file=sys.stderr,
                flush=True,
            )
            env = dict(os.environ, JAX_PLATFORMS="cpu", _SHEEPRL_BENCH_CPU_FALLBACK="1")
            # exec replaces the process (hung RPC threads included) with a clean
            # CPU-pinned copy of this same invocation
            os.execve(sys.executable, [sys.executable, os.path.abspath(__file__), *sys.argv[1:]], env)

    threading.Thread(target=_watchdog, daemon=True).start()
    import jax

    jax.devices()
    probe_done.set()

    # stdout must carry EXACTLY one JSON line: the CLI's config dump and progress
    # prints go to stderr instead
    with contextlib.redirect_stdout(sys.stderr):
        if cli_args.smoke:
            result = bench_smoke()
        else:
            result = {}
            if cli_args.target in ("ppo", "all"):
                result = bench_ppo()
                _regression_check(result)
            if cli_args.target in ("dv3", "all"):
                try:
                    dv3 = bench_dv3()
                    result.update(dv3)
                    if cli_args.target == "dv3":
                        result.setdefault("metric", headline_metric)
                        result.setdefault("value", dv3.get("dv3_gsteps_per_sec"))
                        result.setdefault("unit", "g-steps/s")
                        result.setdefault("vs_baseline", dv3.get("dv3_vs_baseline"))
                except Exception as e:  # a DV3 bench failure must not lose the PPO number
                    result["dv3_error"] = f"{type(e).__name__}: {e}"
                try:
                    # the Atari-100K training recipe shape (batch 16 x seq 64)
                    result.update(bench_dv3(batch=16, key_prefix="dv3_recipe"))
                except Exception as e:
                    result["dv3_recipe_error"] = f"{type(e).__name__}: {e}"
            if cli_args.target in ("compile", "all"):
                try:
                    comp = bench_compile()
                    result.update(comp)
                    if cli_args.target == "compile":
                        result.setdefault("metric", headline_metric)
                        result.setdefault("value", comp.get("compile_warm_first_train_step_s"))
                        result.setdefault("unit", "s")
                        result.setdefault("vs_baseline", comp.get("compile_warm_speedup"))
                except Exception as e:  # a compile-bench failure must not lose the other numbers
                    result["compile_error"] = f"{type(e).__name__}: {e}"
            if cli_args.target == "health":
                # opt-in only (not part of "all"): a CPU-backend resilience
                # drill, not an accelerator throughput number
                health = bench_health()
                result.update(health)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", health.get("health_detection_latency_s"))
                result.setdefault("unit", "s")
            if cli_args.target == "orchestrate":
                # opt-in only, like health: a CPU-backend fleet drill measuring
                # the population controller, not the accelerator
                orch = bench_orchestrate()
                result.update(orch)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", orch.get("orchestrate_preempt_recovery_s"))
                result.setdefault("unit", "s")
            if cli_args.target == "serve":
                # opt-in only: offered-QPS sweep over the policy-serving
                # runtime (subprocess server on the session's backend)
                sv = bench_serve()
                result.update(sv)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", sv.get("serve_p99_ms"))
                result.setdefault("unit", "ms")
                result.setdefault("vs_baseline", None)
            if cli_args.target == "serve_fleet":
                # opt-in only: SLO-gated availability sweep through the
                # failover router while the replica fleet absorbs a SIGKILL
                # and a rolling certified deploy (CPU-backend chaos drill)
                svf = bench_serve_fleet()
                result.update(svf)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", svf.get("serve_fleet_p99_ms"))
                result.setdefault("unit", "ms")
                result.setdefault("vs_baseline", None)
            if cli_args.target == "ingraph":
                # opt-in only: head-to-head of the in-graph vectorized backend
                # (envs/ingraph/) against the host gym path on the same algo
                # settings; the headline is the rollout-phase env-steps/s
                ig = bench_ingraph()
                result.update(ig)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", ig.get("ingraph_env_steps_per_sec"))
                result.setdefault("unit", "env-steps/s")
                result.setdefault("vs_baseline", ig.get("ingraph_vs_host_x"))
            if cli_args.target == "ingraph_train":
                # opt-in only: the whole-iteration fused trainer (collect + GAE
                # + update in one program) vs the same-session collect-only
                # number — the aggregate-throughput headline for the fused path
                igt = bench_ingraph_train()
                result.update(igt)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", igt.get("ingraph_fused_train_env_steps_per_sec"))
                result.setdefault("unit", "env-steps/s")
                result.setdefault("vs_baseline", igt.get("vs_baseline"))
            if cli_args.target == "telemetry":
                # opt-in only: span-tracer overhead on the AOT-warmed fused
                # PPO loop (spans-on vs spans-off vs no-seams baseline) with
                # MFU auto-computed from the executable's own cost_analysis
                tel = bench_telemetry()
                result.update(tel)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", tel.get("telemetry_tracer_overhead_pct"))
                result.setdefault("unit", "%")
                result.setdefault("vs_baseline", None)
            if cli_args.target == "rssm":
                # opt-in only: fused-RSSM step-kernel microbench — flax scan vs
                # the fused custom_vjp formulation at the same shapes, headline
                # is cost_analysis bytes-accessed per scan step (the ISSUE 16
                # >=25%-reduction gate; deterministic on any backend)
                rs = bench_rssm()
                result.update(rs)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", rs.get("rssm_fused_bytes_per_step"))
                result.setdefault("unit", "bytes/step")
                result.setdefault("vs_baseline", rs.get("rssm_bytes_reduction_pct"))
            if cli_args.target == "fsdp":
                # opt-in only: DDP-vs-FSDP-vs-overlap step time + per-shard
                # handoff bytes on the 8-device virtual mesh (subprocess child;
                # folds the retired scripts/fsdp_bench.py into the sentinel)
                fs = bench_fsdp()
                result.update(fs)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", fs.get("fsdp_handoff_bytes_per_iter"))
                result.setdefault("unit", "bytes/iter")
                result.setdefault("vs_baseline", fs.get("fsdp_handoff_reduction_x"))
            if cli_args.target == "checkpoint":
                # opt-in only: sharded-checkpoint drill on the 8-device
                # virtual mesh (subprocess child) — train-thread blocked ms
                # (async vs legacy), commit-to-visible latency, elastic
                # 8->2-device restore wall, and the peer-RAM fetch wall
                ckb = bench_checkpoint()
                result.update(ckb)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", ckb.get("checkpoint_blocked_save_ms"))
                result.setdefault("unit", "ms")
                result.setdefault("vs_baseline", ckb.get("checkpoint_blocked_reduction_x"))
            if cli_args.target == "population":
                # opt-in only: the device-resident vmapped PBT population
                # (one compiled program, one trainee process) vs the classic
                # subprocess-per-trial fleet at the same training budget, plus
                # the forced-8-device member-sharded mesh scaling (subprocess
                # children on the CPU backend)
                pop = bench_population()
                result.update(pop)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", pop.get("population_agg_env_steps_per_sec"))
                result.setdefault("unit", "env-steps/s")
                result.setdefault("vs_baseline", pop.get("population_fused_speedup_x"))
            if cli_args.target == "transport":
                # opt-in only: host control-plane latency/throughput drill
                # (sockets + failpoints; no accelerator involved at all)
                tr = bench_transport()
                result.update(tr)
                result.setdefault("metric", headline_metric)
                result.setdefault("value", tr.get("transport_chunk_roundtrip_ms"))
                result.setdefault("unit", "ms")
                result.setdefault("vs_baseline", None)
    if os.environ.get("_SHEEPRL_BENCH_CPU_FALLBACK"):
        # numbers are real but from the CPU backend — flag them as incomparable
        result["cpu_fallback"] = True
        result["status"] = "cpu_fallback"
        result["warning"] = "accelerator unreachable: results measured on the CPU fallback backend"
    # every record now carries an explicit status: "ok" (measured on the chosen
    # backend), "cpu_fallback" (measured, but on the fallback), or "skipped"
    # (the watchdog's double-timeout record above — no measurement at all)
    result.setdefault("status", "ok")
    result.update(_provenance())
    try:
        # peak HBM across devices (null on backends without memory_stats, i.e.
        # CPU): the regression sentinel's memory-footprint signal
        from sheeprl_tpu.telemetry.device import hbm_gauges

        _peak = hbm_gauges().get("Device/hbm_peak_bytes_max")
        if _peak is not None:
            result["device_hbm_peak_bytes"] = _peak
    except Exception:
        pass
    _append_ledger(dict(result), cli_args.ledger)
    print(json.dumps(result))
