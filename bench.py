"""Benchmark entrypoint for the driver: prints ONE JSON line.

Metric: PPO env-steps/sec on CartPole-v1 (BASELINE.md target metric #1). The
reference anchor is the README PPO wall-clock benchmark: 81.27 s for 65_536 steps on
4 CPUs => ~806 env-steps/sec (sheeprl v0.5.5, SB3 comparison table README.md:99-115).
"""

from __future__ import annotations

import contextlib
import json
import sys
import time


def bench_ppo(total_steps: int = 65536) -> dict:
    from sheeprl_tpu.cli import run

    t0 = time.perf_counter()
    run(
        overrides=[
            "exp=ppo",
            f"algo.total_steps={total_steps}",
            "algo.rollout_steps=128",
            "algo.per_rank_batch_size=64",
            "env.num_envs=8",
            "env.sync_env=True",
            "env.capture_video=False",
            "algo.mlp_keys.encoder=[state]",
            "algo.run_test=False",
            "metric.log_level=0",
            "metric.disable_timer=True",
            "checkpoint.every=999999999",
            "checkpoint.save_last=False",
            "buffer.memmap=False",
        ]
    )
    elapsed = time.perf_counter() - t0
    steps_per_sec = total_steps / elapsed
    baseline_sps = 65536 / 81.27  # reference PPO benchmark: 65536 steps / 81.27 s (README.md:99-115)
    return {
        "metric": "ppo_cartpole_env_steps_per_sec",
        "value": round(steps_per_sec, 2),
        "unit": "env-steps/s",
        "vs_baseline": round(steps_per_sec / baseline_sps, 3),
    }


if __name__ == "__main__":
    # stdout must carry EXACTLY one JSON line: the CLI's config dump and progress
    # prints go to stderr instead
    with contextlib.redirect_stdout(sys.stderr):
        result = bench_ppo()
    print(json.dumps(result))
